"""Version shims for jax APIs this codebase targets.

The code is written against the current jax surface (``jax.shard_map`` with
``check_vma``/``axis_names``, ``jax.typeof``, ``ShapeDtypeStruct(vma=...)``,
``jax.enable_x64``); the pinned jaxlib in some environments (0.4.x) predates
those spellings. Everything funnels through here so call sites stay written
in the modern API:

- :func:`shard_map` — maps ``check_vma`` -> ``check_rep`` and
  ``axis_names`` -> the complementary ``auto`` set on old jax.
- :func:`typeof` — ``jax.typeof`` or the aval via ``jax.core.get_aval``
  (whose aval has no ``vma`` attribute, so vma unions read as empty — the
  old check_rep machinery tracks replication itself).
- :func:`shape_dtype_struct` — drops the ``vma=`` kwarg when unsupported.
"""
from __future__ import annotations

import jax

__all__ = ["shard_map", "typeof", "shape_dtype_struct",
           "supports_partial_manual"]

_HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")
_HAS_TYPEOF = hasattr(jax, "typeof")
try:
    jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
    _SDS_HAS_VMA = True
except TypeError:
    _SDS_HAS_VMA = False


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=True):
    """``jax.shard_map`` facade over both keyword surfaces."""
    if _HAS_NATIVE_SHARD_MAP:
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    # check_rep=False always: old jax's replication checker has no rule for
    # sharding_constraint (its own error message recommends disabling it),
    # and the callers' vma annotations (_pvary) are no-ops here anyway
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False, auto=auto)


def supports_partial_manual() -> bool:
    """Whether this jax can run partial-manual shard_map regions (some mesh
    axes manual, the rest auto/GSPMD). The old experimental shard_map's
    ``auto=`` path raises NotImplementedError for several collectives and
    lowers ``axis_index`` to a PartitionId instruction that XLA's SPMD
    partitioner rejects; native ``jax.shard_map`` (with ``axis_names``)
    handles both. Tests that need partial-manual gate on this."""
    return _HAS_NATIVE_SHARD_MAP


def typeof(x):
    if _HAS_TYPEOF:
        return jax.typeof(x)
    return jax.core.get_aval(x)


def shape_dtype_struct(shape, dtype, vma=frozenset()):
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)
