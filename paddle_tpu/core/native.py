"""Native runtime loader: builds (once) and binds csrc/ via ctypes.

The reference's native runtime is compiled into libpaddle; here the native
pieces (TCPStore rendezvous, DataLoader batch assembly) compile on first use
with the system toolchain and load with ctypes — no pybind11 in this image.
Everything gates gracefully: ``available()`` is False when no compiler
exists, and every consumer has a pure-Python fallback.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from ..analysis import locksan

_LOCK = locksan.Lock("native.load")
_LIB = None
_TRIED = False

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "build", "libpaddle_tpu_native.so")


def _build():
    r = subprocess.run(["make", "-C", _CSRC], capture_output=True, text=True,
                       timeout=300)
    if r.returncode != 0:
        raise RuntimeError(f"native build failed:\n{r.stdout}\n{r.stderr}")


def load():
    """The bound library, or None if it can't be built here."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            # make's dependency check is cheap and keeps the binary in sync
            # with edited sources; fall back to a prebuilt .so if make is
            # unavailable but the artifact exists
            try:
                _build()
            except (RuntimeError, subprocess.SubprocessError, OSError):
                if not os.path.exists(_SO):
                    raise
            lib = ctypes.CDLL(_SO)
            _bind(lib)  # AttributeError here = stale-ABI binary
        except (OSError, RuntimeError, subprocess.SubprocessError,
                AttributeError):
            return None
        _LIB = lib
        return _LIB


def available() -> bool:
    return load() is not None


def _bind(lib):
    c = ctypes
    lib.ts_server_start.restype = c.c_void_p
    lib.ts_server_start.argtypes = [c.c_int]
    lib.ts_server_port.restype = c.c_int
    lib.ts_server_port.argtypes = [c.c_void_p]
    lib.ts_server_stop.argtypes = [c.c_void_p]
    lib.ts_connect.restype = c.c_int
    lib.ts_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.ts_set.restype = c.c_int
    lib.ts_set.argtypes = [c.c_int, c.c_char_p, c.c_uint32, c.c_char_p, c.c_uint32]
    lib.ts_get.restype = c.c_int
    lib.ts_get.argtypes = [c.c_int, c.c_char_p, c.c_uint32, c.c_char_p, c.c_uint32]
    lib.ts_add.restype = c.c_int64
    lib.ts_add.argtypes = [c.c_int, c.c_char_p, c.c_uint32, c.c_int64]
    lib.ts_wait.restype = c.c_int
    lib.ts_wait.argtypes = [c.c_int, c.c_char_p, c.c_uint32, c.c_int64]
    lib.ts_delete.restype = c.c_int
    lib.ts_delete.argtypes = [c.c_int, c.c_char_p, c.c_uint32]
    lib.ts_close.argtypes = [c.c_int]

    lib.bt_create.restype = c.c_void_p
    lib.bt_create.argtypes = [c.c_int64, c.c_int, c.c_int64]
    lib.bt_add_source.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.bt_start.argtypes = [c.c_void_p, c.POINTER(c.c_int64), c.c_int64]
    lib.bt_num_batches.restype = c.c_int64
    lib.bt_num_batches.argtypes = [c.c_void_p]
    lib.bt_next.restype = c.c_int64
    lib.bt_next.argtypes = [c.c_void_p, c.POINTER(c.c_char_p), c.c_uint64]
    lib.bt_destroy.argtypes = [c.c_void_p]
