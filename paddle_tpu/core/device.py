"""Device / place management.

The reference dispatches kernels on ``phi::Place`` (CPU/GPU/XPU/Custom —
/root/reference/paddle/phi/common/place.h:28) with a DeviceContext pool and
per-place allocators. On TPU the XLA runtime owns devices, streams and memory,
so a Place reduces to a handle onto a ``jax.Device``; ``set_device`` installs a
default placement used by creation ops.
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


class Place:
    """Device identity: ``Place("tpu", 0)``, ``Place("cpu")``.

    TPU-native analogue of ``phi::Place``: no allocation-type axis (XLA owns
    memory), just a backend name + device index.
    """

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str = "tpu", device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    @property
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _platform_matches(d.platform, self.device_type)]
        if not devs:
            # fall back to whatever the default backend offers (CI has CPU only)
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type in ("tpu", "axon")

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"


CPUPlace = lambda: Place("cpu", 0)  # noqa: E731 - paddle-API-shaped constructors
TPUPlace = lambda idx=0: Place("tpu", idx)  # noqa: E731


def _platform_matches(platform: str, device_type: str) -> bool:
    if device_type in ("tpu", "axon"):
        return platform in ("tpu", "axon")
    return platform == device_type


def set_device(device: str) -> Place:
    """``paddle.device.set_device("tpu:0")`` equivalent."""
    if ":" in device:
        dev_type, _, idx = device.partition(":")
        place = Place(dev_type, int(idx))
    else:
        place = Place(device, 0)
    _state.place = place
    return place


def get_device() -> str:
    place = get_place()
    return f"{place.device_type}:{place.device_id}"


def get_place() -> Place:
    place = getattr(_state, "place", None)
    if place is None:
        # default to the best available backend
        platform = jax.default_backend()
        place = Place("tpu" if platform in ("tpu", "axon") else platform, 0)
        _state.place = place
    return place


def device_count(device_type: str | None = None) -> int:
    if device_type is None:
        return jax.device_count()
    return len([d for d in jax.devices() if _platform_matches(d.platform, device_type)])


def is_compiled_with_tpu() -> bool:
    return any(d.platform in ("tpu", "axon") for d in jax.devices())
