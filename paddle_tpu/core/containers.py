"""Tensor container types beyond DenseTensor (reference phi:
TensorArray — framework/lod_tensor_array + python/paddle/tensor/array.py;
SelectedRows — phi/core/selected_rows.h, sparse row-gradient container).

TPU-native: a TensorArray is a host-side list feeding lax.scan stacking (the
dynamic-loop role the reference gives it in while_loop); SelectedRows is the
(rows, values) pair our embedding-style sparse grads use before a
segment-sum scatter into the dense table.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

__all__ = ["TensorArray", "SelectedRows",
           "create_array", "array_write", "array_read", "array_length"]


class TensorArray:
    """Append-only list of same-rank Tensors with stack/concat views."""

    def __init__(self, dtype="float32"):
        self.dtype = dtype
        self._items: list[Tensor] = []

    def append(self, t):
        self._items.append(t if isinstance(t, Tensor) else Tensor(t))
        return self

    def write(self, index, t):
        index = int(index)
        if index > len(self._items):
            # reference array_write only permits i <= len (append position);
            # gap-filling with placeholders would poison stack()/read()
            raise IndexError(
                f"array_write index {index} > length {len(self._items)}")
        if index == len(self._items):
            self._items.append(None)
        self._items[index] = t if isinstance(t, Tensor) else Tensor(t)
        return self

    def read(self, index):
        return self._items[int(index)]

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def stack(self, axis=0):
        from .. import ops as P

        return P.stack(self._items, axis=axis)

    def concat(self, axis=0):
        from .. import ops as P

        return P.concat(self._items, axis=axis)

    def pop(self, index=-1):
        return self._items.pop(index)


class SelectedRows:
    """Sparse row container: `rows[i]` indexes the dense height dim,
    `values[i]` is that row's data (reference selected_rows.h)."""

    def __init__(self, rows, values, height):
        self.rows = jnp.asarray(
            rows.numpy() if isinstance(rows, Tensor) else rows, jnp.int32)
        self.values = values._value if isinstance(values, Tensor) \
            else jnp.asarray(values)
        self.height = int(height)

    def to_dense(self):
        """Duplicate rows accumulate (the merge_selected_rows semantic)."""
        out = jax.ops.segment_sum(self.values, self.rows.astype(jnp.int32),
                                  self.height)
        return Tensor._wrap(out)

    def merge(self):
        """Coalesce duplicate rows (reference merge_selected_rows op)."""
        uniq = np.unique(np.asarray(jax.device_get(self.rows)))
        dense = self.to_dense()._value
        return SelectedRows(uniq, dense[jnp.asarray(uniq)], self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nrows={self.rows.shape[0]})")


# -- paddle.tensor.array functional surface --------------------------------
def create_array(dtype="float32", initialized_list=None):
    arr = TensorArray(dtype)
    for t in initialized_list or []:
        arr.append(t)
    return arr


def array_write(x, i, array=None):
    if array is None:
        array = TensorArray()
    array.write(int(i.numpy()) if isinstance(i, Tensor) else int(i), x)
    return array


def array_read(array, i):
    return array.read(int(i.numpy()) if isinstance(i, Tensor) else int(i))


def array_length(array):
    return Tensor(np.asarray(len(array), np.int64))
