"""Tensor: a thin mutable shell over ``jax.Array``.

Plays the role of the reference's ``phi::DenseTensor`` + eager ``Tensor``
(/root/reference/paddle/phi/core/dense_tensor.h:43 and
 /root/reference/paddle/fluid/eager/autograd_meta.h:61): holds the device
array, the autograd metadata (``stop_gradient``, ``.grad``, producing
``GradNode``) and the user-facing method surface. Memory, layout and device
placement live inside XLA — there is no allocator or DeviceContext here.

Mutability (``set_value``, in-place optimizer updates, ``__setitem__``) is
implemented by swapping the wrapped immutable ``jax.Array``; under jit the
same modules run functionally over their state pytrees instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from .autograd import backward as _backward_engine
from .device import get_place

__all__ = ["Tensor", "Parameter", "to_tensor"]


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_output_index",
        "_retain_grad",
        "_grad_hooks",
        "name",
        "persistable",
        "trainable",
        "sharding_spec",  # PartitionSpec annotation used by distributed engine
        "placements",  # auto-parallel marker (dist.Shard/Replicate list)
        "process_mesh",  # auto-parallel ProcessMesh annotation
        "_recompute",  # static-graph replay closure (paddle_tpu.static)
        "__weakref__",
    )

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True, name=None):
        if data is None:
            value = jnp.zeros((), dtype_mod.convert_dtype(dtype or "float32"))
        else:
            value = _to_jax_array(data, dtype)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._output_index = 0
        self._retain_grad = False
        self._grad_hooks = []
        self.name = name
        self.persistable = False
        self.trainable = True
        self.sharding_spec = None
        self._recompute = None

    # -- construction -----------------------------------------------------
    @classmethod
    def _wrap(cls, value, stop_gradient=True, node=None, output_index=0, name=None):
        t = cls.__new__(cls)
        t._value = value
        t.stop_gradient = stop_gradient
        t._grad = None
        t._grad_node = node
        t._output_index = output_index
        t._retain_grad = False
        t._grad_hooks = []
        t.name = name
        t.persistable = False
        t.trainable = True
        t.sharding_spec = None
        t._recompute = None
        return t

    # -- metadata ---------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def place(self):
        try:
            devs = self._value.devices()
            dev = next(iter(devs))
            from .device import Place

            plat = dev.platform
            return Place("tpu" if plat in ("tpu", "axon") else plat, dev.id)
        except Exception:
            return get_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    # -- autograd ---------------------------------------------------------
    @property
    def grad(self):
        if self._grad is None:
            return None
        return Tensor._wrap(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad = None
        else:
            self._grad = value._value if isinstance(value, Tensor) else jnp.asarray(value)

    def backward(self, grad_tensor=None, retain_graph=False):
        _backward_engine([self], [grad_tensor], retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def retain_grads(self):
        self._retain_grad = True

    def register_hook(self, hook):
        self._grad_hooks.append(hook)

        class _Removable:
            def remove(inner):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass

        return _Removable()

    def detach(self):
        return Tensor._wrap(self._value, stop_gradient=True, name=self.name)

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    # -- value access -----------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    def set_value(self, value):
        new = _to_jax_array(value, self.dtype)
        if tuple(new.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {new.shape} vs {self._value.shape}"
            )
        self._value = new
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def clone(self):
        from .dispatch import apply

        return apply(lambda x: x + 0, self, op_name="clone")

    # -- dunder glue (full op surface is patched in by paddle_tpu.ops) ----
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __hash__(self):
        return id(self)

    def __repr__(self):
        prefix = "Tensor" if not isinstance(self, Parameter) else "Parameter"
        return (
            f"{prefix}(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}, "
            f"stop_gradient={self.stop_gradient},\n       {self._value})"
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, idx):
        from .dispatch import apply

        idx = _unwrap_index(idx)
        return apply(lambda x: x[idx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        val = value._value if isinstance(value, Tensor) else value
        self._value = self._value.at[idx].set(val)

    # -- misc parity helpers ---------------------------------------------
    def cpu(self):
        return Tensor._wrap(jax.device_get(self._value), stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and (a in dtype_mod._NAME_TO_DTYPE):
                t = t.astype(a)
            elif isinstance(a, (np.dtype, type)):
                t = t.astype(a)
        return t

    def astype(self, dtype):
        from .dispatch import apply

        nd = dtype_mod.convert_dtype(dtype)
        return apply(lambda x: x.astype(nd), self, op_name="cast")

    cast = astype

    def _block_until_ready(self):
        jax.block_until_ready(self._value)
        return self


class Parameter(Tensor):
    """Trainable leaf tensor (``paddle.create_parameter`` /
    ``EagerParamBase``, /root/reference/python/paddle/fluid/framework.py)."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable


def _to_jax_array(data, dtype=None):
    nd = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    if isinstance(data, Tensor):
        value = data._value
        return value.astype(nd) if nd is not None and value.dtype != nd else value
    if isinstance(data, (jax.Array,)):
        return data.astype(nd) if nd is not None and data.dtype != nd else data
    if isinstance(data, np.ndarray):
        if nd is None and data.dtype == np.float64:
            nd = np.dtype(np.float64)  # preserve numpy dtypes exactly
        return jnp.asarray(data, dtype=nd)
    if isinstance(data, (bool, int, float, complex)):
        if nd is None:
            if isinstance(data, bool):
                nd = np.dtype(bool)
            elif isinstance(data, int):
                nd = np.dtype(np.int64)
            elif isinstance(data, float):
                nd = dtype_mod.convert_dtype(dtype_mod.get_default_dtype())
            else:
                nd = np.dtype(np.complex64)
        return jnp.asarray(data, dtype=nd)
    # lists/tuples and anything numpy understands
    arr = np.asarray(data)
    if nd is None and arr.dtype == np.float64:
        nd = dtype_mod.convert_dtype(dtype_mod.get_default_dtype())
    return jnp.asarray(arr, dtype=nd)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """``paddle.to_tensor``."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
