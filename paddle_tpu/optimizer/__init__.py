"""paddle.optimizer parity surface."""
from . import lr  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Momentum,
    RMSProp,
)

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "RMSProp",
    "Adadelta", "Adamax", "Lamb", "LBFGS", "lr",
]
