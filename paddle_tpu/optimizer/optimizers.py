"""Concrete optimizers: SGD/Momentum/Adam/AdamW/Adagrad/RMSProp/Adadelta/
Adamax/Lamb (parity: /root/reference/python/paddle/optimizer/*.py).
Update rules are pure jnp — XLA fuses each into a single elementwise kernel,
playing the role of the reference's fused/multi-tensor optimizer kernels."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "RMSProp", "Adadelta", "Adamax", "Lamb"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def update(self, param, grad, state, lr):
        return param - lr * grad, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, param_value):
        return {"velocity": jnp.zeros_like(param_value)}

    def update(self, param, grad, state, lr):
        v = self._momentum * state["velocity"] + grad
        if self._nesterov:
            new_p = param - lr * (grad + self._momentum * v)
        else:
            new_p = param - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, param_value):
        return {
            "moment1": jnp.zeros_like(param_value),
            "moment2": jnp.zeros_like(param_value),
            "beta1_pow": jnp.ones((), param_value.dtype),
            "beta2_pow": jnp.ones((), param_value.dtype),
        }

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"] + (1 - b1) * grad
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(grad)
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        new_p = param - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def update(self, param, grad, state, lr, decay=True):
        wd = float(self._weight_decay or 0.0)
        if wd and decay:
            param = param * (1.0 - lr * wd)
        return super().update(param, grad, state, lr)

    @property
    def _wd_step(self):
        return float(self._weight_decay or 0.0)

    def step(self):
        # honor apply_decay_param_fun by masking decay per-parameter
        if self._apply_decay_param_fun is None:
            return super().step()
        fn = self._apply_decay_param_fun
        from ..core.autograd import no_grad
        from ..core.tensor import Tensor

        with no_grad():
            lr = self.get_lr()
            params = self._parameter_list or []
            grads_and_params = [
                (p, p._grad) for p in params if p._grad is not None and p.trainable
            ]
            if self._grad_clip is not None:
                clipped = self._grad_clip(
                    [(p, Tensor._wrap(g)) for p, g in grads_and_params]
                )
                grads_and_params = [(p, g._value) for p, g in clipped]
            for p, g in grads_and_params:
                g = g.astype(p._value.dtype)
                st = self._state_for(p)
                decay = bool(fn(p.name)) if p.name else True
                new_p, new_st = self.update(p._value, g, st, lr, decay=decay)
                p._value = new_p
                self._accumulators[id(p)] = new_st
            self._step_count += 1


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, param_value):
        return {"moment": jnp.full_like(param_value, self._init_acc)}

    def update(self, param, grad, state, lr):
        acc = state["moment"] + jnp.square(grad)
        return param - lr * grad / (jnp.sqrt(acc) + self._eps), {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered

    def init_state(self, param_value):
        st = {
            "mean_square": jnp.zeros_like(param_value),
            "momentum": jnp.zeros_like(param_value),
        }
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(param_value)
        return st

    def update(self, param, grad, state, lr):
        rho = self._rho
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(grad)
        st = {"mean_square": ms}
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
            st["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum"] + lr * grad / denom
        st["momentum"] = mom
        return param - mom, st


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon

    def init_state(self, param_value):
        return {
            "avg_squared_grad": jnp.zeros_like(param_value),
            "avg_squared_update": jnp.zeros_like(param_value),
        }

    def update(self, param, grad, state, lr):
        rho, eps = self._rho, self._eps
        asg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(grad)
        upd = jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(asg + eps) * grad
        asu = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(upd)
        return param - lr * upd, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, param_value):
        return {
            "moment": jnp.zeros_like(param_value),
            "inf_norm": jnp.zeros_like(param_value),
            "beta1_pow": jnp.ones((), param_value.dtype),
        }

    def update(self, param, grad, state, lr):
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        m = b1 * state["moment"] + (1 - b1) * grad
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(grad))
        new_p = param - lr / (1 - b1p) * m / (u + self._eps)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, param_value):
        return {
            "moment1": jnp.zeros_like(param_value),
            "moment2": jnp.zeros_like(param_value),
            "beta1_pow": jnp.ones((), param_value.dtype),
            "beta2_pow": jnp.ones((), param_value.dtype),
        }

    def update(self, param, grad, state, lr):
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"] + (1 - b1) * grad
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(grad)
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._wd * param
        w_norm = jnp.linalg.norm(param)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = param - lr * trust * r
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}
