"""Optimizers (parity: /root/reference/python/paddle/optimizer/optimizer.py:91).

Design: each optimizer is a *pure update rule* ``_update(param, grad, state,
lr) -> (new_param, new_state)`` plus the mutable shell (``step``,
``clear_grad``, ``minimize``, ``state_dict``). The eager path applies the rule
to ``param.grad``; jitted train steps (hapi/fleet/bench) call the same rule
inside ``jax.jit`` via ``apply_gradients`` on raw pytrees — one code path for
both, the reference's fused optimizer kernels become XLA-fused update lambdas.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        from . import lr as lr_mod

        self._lr = learning_rate
        self._lr_scheduler = learning_rate if isinstance(learning_rate, lr_mod.LRScheduler) else None
        self._parameter_list = list(parameters) if parameters is not None else None
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators = {}  # id(param) -> state dict of raw arrays
        self._step_count = 0

    # -- learning rate ----------------------------------------------------
    def get_lr(self):
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler.get_lr())
        return float(self._lr)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value

    # -- state ------------------------------------------------------------
    def init_state(self, param_value):
        """Return the initial state pytree (dict of arrays) for one param."""
        return {}

    def update(self, param, grad, state, lr):
        """Pure update rule -> (new_param, new_state). Override."""
        raise NotImplementedError

    def _state_for(self, p):
        st = self._accumulators.get(id(p))
        if st is None:
            st = self.init_state(p._value)
            self._accumulators[id(p)] = st
        return st

    # -- eager step -------------------------------------------------------
    @no_grad()
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("Optimizer constructed without parameters")
        lr = self.get_lr()
        grads_and_params = [(p, p._grad) for p in params if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            clipped = self._grad_clip(
                [(p, Tensor._wrap(g)) for p, g in grads_and_params]
            )
            grads_and_params = [(p, g._value) for p, g in clipped]
        for p, g in grads_and_params:
            g = g.astype(p._value.dtype)
            if self._weight_decay and not isinstance(self._weight_decay, str) and \
                    not getattr(self, "_decoupled_wd", False):
                g = g + float(self._weight_decay) * p._value
            st = self._state_for(p)
            new_p, new_st = self.update(p._value, g, st, lr)
            p._value = new_p
            self._accumulators[id(p)] = new_st
        self._step_count += 1

    def clear_grad(self, set_to_zero=True):
        if self._parameter_list is not None:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # -- functional path for jit ------------------------------------------
    def init_state_tree(self, params):
        """params: dict name -> array. Returns state pytree."""
        return {k: self.init_state(v) for k, v in params.items()}

    def apply_gradients(self, params, grads, state, lr=None, weight_decay_mask=None):
        """Pure: returns (new_params, new_state). Usable inside jax.jit.

        ``weight_decay_mask``: dict name->bool; False exempts a param from
        decay (e.g. biases/norms, mirroring the reference's no-decay lists).
        """
        lr = self.get_lr() if lr is None else lr
        if self._grad_clip is not None:
            if not hasattr(self._grad_clip, "_clip_tree"):
                # loud, not silent: a clip that only speaks the eager
                # [(param, grad)] protocol can't run inside this jitted path
                raise TypeError(
                    f"{type(self._grad_clip).__name__} has no _clip_tree; "
                    "jitted training (engine/hapi) needs a pytree-capable "
                    "clip — subclass paddle_tpu.nn.clip._ClipBase or add a "
                    "_clip_tree(grads: dict) method")
            # grads here are (possibly mesh-sharded) global arrays, so the
            # clip's norm reductions span every parallel axis — the
            # reference HybridParallelClipGrad cross-group behavior
            present = {k: g for k, g in grads.items() if g is not None}
            clipped = self._grad_clip._clip_tree(present)
            grads = {k: clipped.get(k, g) for k, g in grads.items()}
        new_params, new_state = {}, {}
        for k, p in params.items():
            g = grads[k]
            if g is None:
                new_params[k], new_state[k] = p, state[k]
                continue
            g = g.astype(p.dtype)
            decay_ok = weight_decay_mask.get(k, True) if weight_decay_mask else True
            if self._weight_decay and not getattr(self, "_decoupled_wd", False) and decay_ok:
                g = g + float(self._weight_decay) * p
            new_params[k], new_state[k] = self.update(
                p, g, state[k], lr, decay=decay_ok
            ) if self._takes_decay() else self.update(p, g, state[k], lr)
        return new_params, new_state

    def _takes_decay(self):
        import inspect

        return "decay" in inspect.signature(self.update).parameters

    # -- serialization ----------------------------------------------------
    def _param_keys(self):
        """Accumulator keys aligned with the parameter list: the parameter's
        name when it has one (mirroring the reference's name-based .pdopt
        layout), positional for unnamed params. Duplicate names get a
        deterministic ``__<n>`` suffix on both save and load so state never
        silently collides."""
        keys, seen = [], {}
        for i, p in enumerate(self._parameter_list or []):
            key = p.name if getattr(p, "name", None) else f"param{i}"
            n = seen.get(key, 0)
            seen[key] = n + 1
            keys.append(key if n == 0 else f"{key}__{n}")
        return keys

    def state_dict(self):
        out = {"_step_count": self._step_count}
        if self._parameter_list is not None:
            for p, key in zip(self._parameter_list, self._param_keys()):
                st = self._accumulators.get(id(p))
                if st:
                    for k, v in st.items():
                        out[f"{key}.{k}"] = Tensor._wrap(v)
        if self._lr_scheduler is not None:
            out["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("_step_count", 0)
        if self._parameter_list is not None:
            for p, key in zip(self._parameter_list, self._param_keys()):
                prefix = f"{key}."
                st = {}
                for k, v in state.items():
                    if isinstance(k, str) and k.startswith(prefix):
                        st[k[len(prefix):]] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                if st:
                    self._accumulators[id(p)] = st
        if self._lr_scheduler is not None and "LR_Scheduler" in state:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])
