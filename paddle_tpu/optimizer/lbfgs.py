"""L-BFGS with strong-Wolfe line search.

Parity: /root/reference/python/paddle/optimizer/lbfgs.py:1 (paddle's LBFGS,
itself the classic two-loop-recursion + cubic-interpolation line search of
Nocedal & Wright ch.6-7) and
/root/reference/python/paddle/incubate/optimizer/line_search_dygraph.py.

TPU stance: L-BFGS is a HOST-side driver — each iteration re-evaluates the
user's closure (which may itself be jitted) and does O(m·n) vector math on
the flattened parameters. The curvature history and line search run in
float64 numpy for robustness; only the closure touches the accelerator.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.autograd import enable_grad
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Minimizer of the cubic through (x1,f1,g1), (x2,f2,g2); falls back to
    bisection when the interpolation is ill-conditioned."""
    if bounds is not None:
        xmin_bound, xmax_bound = bounds
    else:
        xmin_bound, xmax_bound = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1 ** 2 - g1 * g2
    if d2_square >= 0:
        d2 = np.sqrt(d2_square)
        if x1 <= x2:
            min_pos = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            min_pos = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return float(min(max(min_pos, xmin_bound), xmax_bound))
    return float((xmin_bound + xmax_bound) / 2.0)


def _strong_wolfe(obj_func, x, t, d, f, g, gtd, c1=1e-4, c2=0.9,
                  tolerance_change=1e-9, max_ls=25):
    """Bracketing strong-Wolfe search: returns (f_new, g_new, t, n_evals).
    ``obj_func(x, t, d)`` evaluates loss+grad at x + t·d."""
    d_norm = np.abs(d).max()
    g = g.copy()
    f_new, g_new = obj_func(x, t, d)
    ls_func_evals = 1
    gtd_new = float(g_new @ d)

    t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
    done = False
    ls_iter = 0
    while ls_iter < max_ls:
        if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
            bracket = [t_prev, t]
            bracket_f = [f_prev, f_new]
            bracket_g = [g_prev, g_new.copy()]
            bracket_gtd = [gtd_prev, gtd_new]
            break
        if abs(gtd_new) <= -c2 * gtd:
            bracket = [t, t]
            bracket_f = [f_new, f_new]
            bracket_g = [g_new, g_new]
            done = True
            break
        if gtd_new >= 0:
            bracket = [t_prev, t]
            bracket_f = [f_prev, f_new]
            bracket_g = [g_prev, g_new.copy()]
            bracket_gtd = [gtd_prev, gtd_new]
            break

        min_step = t + 0.01 * (t - t_prev)
        max_step = t * 10
        tmp = t
        t = _cubic_interpolate(t_prev, f_prev, gtd_prev, t, f_new, gtd_new,
                               bounds=(min_step, max_step))
        t_prev, f_prev, g_prev, gtd_prev = tmp, f_new, g_new.copy(), gtd_new
        f_new, g_new = obj_func(x, t, d)
        ls_func_evals += 1
        gtd_new = float(g_new @ d)
        ls_iter += 1
    else:
        bracket = [0.0, t]
        bracket_f = [f, f_new]
        bracket_g = [g, g_new]

    # zoom phase
    insuf_progress = False
    low_pos, high_pos = (0, 1) if bracket_f[0] <= bracket_f[-1] else (1, 0)
    while not done and ls_iter < max_ls:
        if abs(bracket[1] - bracket[0]) * d_norm < tolerance_change:
            break
        t = _cubic_interpolate(bracket[0], bracket_f[0], bracket_gtd[0],
                               bracket[1], bracket_f[1], bracket_gtd[1])
        eps = 0.1 * (max(bracket) - min(bracket))
        if min(max(bracket) - t, t - min(bracket)) < eps:
            if insuf_progress or t >= max(bracket) or t <= min(bracket):
                t = (max(bracket) - eps if abs(t - max(bracket))
                     < abs(t - min(bracket)) else min(bracket) + eps)
                insuf_progress = False
            else:
                insuf_progress = True
        else:
            insuf_progress = False

        f_new, g_new = obj_func(x, t, d)
        ls_func_evals += 1
        gtd_new = float(g_new @ d)
        ls_iter += 1

        if f_new > (f + c1 * t * gtd) or f_new >= bracket_f[low_pos]:
            bracket[high_pos] = t
            bracket_f[high_pos] = f_new
            bracket_g[high_pos] = g_new.copy()
            bracket_gtd[high_pos] = gtd_new
            low_pos, high_pos = ((0, 1) if bracket_f[0] <= bracket_f[1]
                                 else (1, 0))
        else:
            if abs(gtd_new) <= -c2 * gtd:
                done = True
            elif gtd_new * (bracket[high_pos] - bracket[low_pos]) >= 0:
                bracket[high_pos] = bracket[low_pos]
                bracket_f[high_pos] = bracket_f[low_pos]
                bracket_g[high_pos] = bracket_g[low_pos]
                bracket_gtd[high_pos] = bracket_gtd[low_pos]
            bracket[low_pos] = t
            bracket_f[low_pos] = f_new
            bracket_g[low_pos] = g_new.copy()
            bracket_gtd[low_pos] = gtd_new

    t = bracket[low_pos]
    return bracket_f[low_pos], bracket_g[low_pos], t, ls_func_evals


class LBFGS(Optimizer):
    """Limited-memory BFGS (reference paddle.optimizer.LBFGS).

    ``step(closure)`` drives the whole inner optimization: the closure must
    clear grads, recompute the loss, call ``loss.backward()`` and return the
    loss (same contract as the reference/torch). ``line_search_fn`` is
    ``None`` (fixed learning_rate step) or ``'strong_wolfe'``.
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("only 'strong_wolfe' is supported as "
                             f"line_search_fn, got {line_search_fn!r}")
        if grad_clip is not None:
            # loud, not silent: clipping inside a curvature-history + line
            # search loop would corrupt the quasi-Newton model
            raise ValueError("LBFGS does not support grad_clip")
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._hist = {"old_dirs": [], "old_stps": [], "ro": [],
                      "prev_flat_grad": None, "d": None, "t": None,
                      "h_diag": 1.0, "n_iter": 0, "func_evals": 0}

    # -- flat-vector plumbing over the parameter list ---------------------
    def _params(self):
        if self._parameter_list is None:
            raise ValueError("LBFGS constructed without parameters")
        return [p for p in self._parameter_list if p.trainable]

    def _gather_flat_grad(self):
        chunks = []
        for p in self._params():
            g = p._grad
            flat = (np.zeros(int(np.prod(p.shape)) or 1)
                    if g is None else np.asarray(g, np.float64).ravel())
            if self._weight_decay:
                # L2 regularization folds into the gradient so the line
                # search and curvature pairs see the regularized objective
                flat = flat + float(self._weight_decay) * np.asarray(
                    p._value, np.float64).ravel()
            chunks.append(flat)
        return np.concatenate(chunks) if chunks else np.zeros(0)

    def _clone_flat_params(self):
        return np.concatenate([
            np.asarray(p._value, np.float64).ravel() for p in self._params()])

    def _set_flat_params(self, flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p.shape)) or 1
            p._value = jnp.asarray(
                flat[off:off + n].reshape(tuple(p.shape) or ()),
                dtype=p._value.dtype)
            off += n

    def _evaluate(self, closure, x, t, d):
        """loss+flat grad at x + t·d (params restored by the caller)."""
        self._set_flat_params(x + t * d)
        with enable_grad():
            loss = closure()
        self._hist["func_evals"] += 1
        return float(np.asarray(loss._value)), self._gather_flat_grad()

    # -- the driver -------------------------------------------------------
    def step(self, closure):
        st = self._hist
        lr = self.get_lr()
        with enable_grad():
            orig_loss = closure()
        loss = float(np.asarray(orig_loss._value))
        st["func_evals"] += 1
        current_evals = 1

        flat_grad = self._gather_flat_grad()
        if np.abs(flat_grad).max(initial=0.0) <= self.tolerance_grad:
            return orig_loss

        d, t = st["d"], st["t"]
        old_dirs, old_stps, ro = st["old_dirs"], st["old_stps"], st["ro"]
        h_diag = st["h_diag"]
        prev_flat_grad = st["prev_flat_grad"]
        prev_loss = loss

        n_iter = 0
        while n_iter < self.max_iter:
            n_iter += 1
            st["n_iter"] += 1

            if st["n_iter"] == 1:
                d = -flat_grad
                h_diag = 1.0
            else:
                y = flat_grad - prev_flat_grad
                s = d * t
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(old_dirs) >= self.history_size:
                        old_dirs.pop(0)
                        old_stps.pop(0)
                        ro.pop(0)
                    old_dirs.append(y)
                    old_stps.append(s)
                    ro.append(1.0 / ys)
                    h_diag = ys / float(y @ y)
                # two-loop recursion
                q = -flat_grad.copy()
                al = [0.0] * len(old_dirs)
                for i in range(len(old_dirs) - 1, -1, -1):
                    al[i] = float(old_stps[i] @ q) * ro[i]
                    q -= al[i] * old_dirs[i]
                d = q * h_diag
                for i in range(len(old_dirs)):
                    be_i = float(old_dirs[i] @ d) * ro[i]
                    d += (al[i] - be_i) * old_stps[i]

            prev_flat_grad = flat_grad.copy()
            prev_loss = loss

            gtd = float(flat_grad @ d)
            if gtd > -self.tolerance_change:
                break
            t = (min(1.0, 1.0 / np.abs(flat_grad).sum()) * lr
                 if st["n_iter"] == 1 else lr)

            if self.line_search_fn == "strong_wolfe":
                x_init = self._clone_flat_params()
                loss, flat_grad, t, ls_evals = _strong_wolfe(
                    lambda x, step_t, dd: self._evaluate(closure, x, step_t, dd),
                    x_init, t, d, loss, flat_grad, gtd,
                    tolerance_change=self.tolerance_change)
                self._set_flat_params(x_init + t * d)
                current_evals += ls_evals
            else:
                self._set_flat_params(self._clone_flat_params() + t * d)
                if n_iter != self.max_iter:
                    with enable_grad():
                        loss = float(np.asarray(closure()._value))
                    flat_grad = self._gather_flat_grad()
                    current_evals += 1
                    st["func_evals"] += 1

            if current_evals >= self.max_eval:
                break
            if np.abs(flat_grad).max(initial=0.0) <= self.tolerance_grad:
                break
            if np.abs(d * t).max(initial=0.0) <= self.tolerance_change:
                break
            if abs(loss - prev_loss) < self.tolerance_change:
                break

        st.update(d=d, t=t, prev_flat_grad=prev_flat_grad, h_diag=h_diag)
        self._step_count += 1
        return orig_loss

    def state_dict(self):
        out = super().state_dict()
        st = self._hist
        out["lbfgs_state"] = {
            "old_dirs": [np.asarray(a) for a in st["old_dirs"]],
            "old_stps": [np.asarray(a) for a in st["old_stps"]],
            "ro": list(st["ro"]),
            "prev_flat_grad": st["prev_flat_grad"],
            "d": st["d"], "t": st["t"], "h_diag": st["h_diag"],
            "n_iter": st["n_iter"], "func_evals": st["func_evals"],
        }
        return out

    def set_state_dict(self, state):
        super().set_state_dict(state)
        saved = state.get("lbfgs_state")
        if saved:
            self._hist.update(saved)
            self._hist["old_dirs"] = [np.asarray(a) for a in saved["old_dirs"]]
            self._hist["old_stps"] = [np.asarray(a) for a in saved["old_stps"]]
