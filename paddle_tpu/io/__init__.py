"""paddle.io parity: Dataset / DataLoader / samplers
(reference: /root/reference/python/paddle/io/ — multi-worker shared-memory
loader in dataloader/dataloader_iter.py).

TPU-first stance: the loader produces host numpy batches; device transfer
happens once per step inside the jitted train step (or explicitly via
``to_tensor``). Multi-process workers are unnecessary for the common case —
numpy batching is cheap relative to TPU step time — but a thread-prefetch
queue covers the reader/compute overlap the reference gets from its
shared-memory workers.
"""
from __future__ import annotations

import math
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from ..utils import faults

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset", "ConcatDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "get_worker_info", "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = self.cum[ds_idx - 1] if ds_idx else 0
        return self.datasets[ds_idx][idx - prev]

    def __len__(self):
        return self.cum[-1] if self.cum else 0


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):  # fractions
        n = len(dataset)
        lengths = [int(math.floor(n * f)) for f in lengths]
        lengths[-1] += n - sum(lengths)
    idx = np.random.permutation(sum(lengths))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, idx[off : off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(p), self.num_samples, replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space over data-parallel ranks
    (reference: python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler). On a single-controller TPU mesh this shards by
    process index for multi-host input pipelines."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        if num_replicas is None:
            import jax

            num_replicas = jax.process_count()
        if rank is None:
            import jax

            rank = jax.process_index()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / num_replicas))
        self.total_size = self.num_samples * num_replicas

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]
        local = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id=0, num_workers=1, dataset=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (tuple, list)):
        transposed = zip(*batch)
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _poison_collated(batch):
    """NaN-fill the floating leaves of a collated batch (the
    ``dataloader.next:bad_batch`` chaos fault — a corrupt reader shard)."""
    if isinstance(batch, Tensor):
        arr = np.asarray(batch._value)
        if np.issubdtype(arr.dtype, np.floating):
            return Tensor(np.full_like(arr, np.nan))
        return batch
    if isinstance(batch, (list, tuple)):
        return type(batch)(_poison_collated(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _poison_collated(v) for k, v in batch.items()}
    if isinstance(batch, np.ndarray) and np.issubdtype(batch.dtype,
                                                       np.floating):
        return np.full_like(batch, np.nan)
    return batch


class DataLoader:
    """Batched loader with optional background-thread prefetch
    (the reference's multi-worker loader role, dataloader_iter.py)."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.persistent_workers = persistent_workers
        self._mp_iter = None  # live fleet when persistent_workers
        self.iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        # native batch assembly only understands the uniform default sampler;
        # a user-supplied sampler owns its batching (sizes may vary)
        self._own_sampler = batch_sampler is None and not self.iterable_mode
        if self.iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self.iterable_mode:
            raise TypeError("IterableDataset-backed DataLoader has no len()")
        return len(self.batch_sampler)

    def _native_arrays(self):
        """Contiguous source arrays for the C++ batcher, or None when this
        dataset/config can't use it (custom collate, iterable, transform)."""
        if (self.iterable_mode or self.collate_fn is not default_collate_fn
                or not self._own_sampler):
            return None
        get = getattr(self.dataset, "get_arrays", None)
        if get is None:
            return None
        from .native_batcher import supported

        if not supported():
            return None
        return get()

    def _native_iter(self, arrays):
        """Batch assembly in the C++ worker (reference buffered reader)."""
        from .native_batcher import NativeBatcher

        flat = [i for batch in self.batch_sampler for i in batch]
        nb = NativeBatcher(arrays, flat, self.batch_size,
                           drop_last=self.drop_last,
                           prefetch=max(2, self.prefetch_factor))
        try:
            for outs in nb:
                yield [Tensor(o) for o in outs]
        finally:
            nb.close()

    def _raw_iter(self):
        arrays = self._native_arrays()
        if arrays is not None:
            yield from self._native_iter(arrays)
            return
        if self.iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if self.batch_size is not None and len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        # dataloader.next chaos site (docs/ROBUSTNESS.md): per emitted
        # batch; "bad_batch" NaN-poisons the floats (exercises the
        # numerical-health guard), error/delay propagate as usual. One
        # no-op inject call per batch when no plan is armed.
        for i, batch in enumerate(self._iter_impl()):
            act = faults.inject("dataloader.next", batch=i)
            if act == "bad_batch":
                batch = _poison_collated(batch)
            yield batch

    def _iter_impl(self):
        if self.num_workers > 0:
            # real worker PROCESSES + shared-memory ring (reference
            # dataloader_iter.py multi-process path) — python transform
            # pipelines escape the GIL. The native C++ batcher still wins
            # for plain array datasets, so it keeps precedence.
            arrays = self._native_arrays()
            if arrays is not None:
                yield from self._native_iter(arrays)
                return
            from .worker import MultiProcessLoaderIter

            if self.persistent_workers and not self.iterable_mode:
                # fleet survives across epochs (reference
                # persistent_workers): re-fork only if workers died
                if self._mp_iter is None or not self._mp_iter.alive():
                    if self._mp_iter is not None:
                        # alive() is False if ANY worker died — reap the
                        # survivors + their shm ring before re-forking
                        self._mp_iter.close()
                    self._mp_iter = MultiProcessLoaderIter(self)
                yield from self._mp_iter
                return
            it = MultiProcessLoaderIter(self)
            try:
                yield from it
            finally:
                it.close()
            return
        if not self.use_buffer_reader:
            yield from self._raw_iter()
            return
        # num_workers=0 + buffered reader: single background thread overlaps
        # host batching with device compute (the pre-round-4 >0 path)
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor * max(self.num_workers, 1))
        sentinel = object()
        stop = threading.Event()
        err = []

        def producer():
            try:
                for b in self._raw_iter():
                    while not stop.is_set():
                        try:
                            q.put(b, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surface worker errors to the consumer
                err.append(e)
            finally:
                # the sentinel MUST arrive or the consumer blocks forever —
                # a put_nowait here silently drops it whenever the queue is
                # full at end-of-data (the consumer then drains the queue
                # and hangs); poll-put until delivered or abandoned
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True,
                             name="dataloader-producer")
        t.start()
        try:
            while True:
                b = q.get()
                if b is sentinel:
                    if err:
                        raise err[0]
                    return
                yield b
        finally:
            # consumer abandoned iteration: unblock and retire the producer
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
