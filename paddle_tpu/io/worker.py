"""Multiprocess DataLoader workers with a shared-memory ring.

TPU-native counterpart of the reference's multi-process loader
(/root/reference/python/paddle/io/dataloader/worker.py:1,
 dataloader_iter.py `_DataLoaderIterMultiProcess`, and the C++ shared-memory
tensor transport): ``num_workers>0`` forks worker PROCESSES (escaping the
GIL for python transform pipelines), each owning a ring of reusable
shared-memory slots. Workers collate batches into numpy arrays, write the
bytes into a free ring slot, and send (skeleton, array specs) through a
result queue; the parent re-assembles Tensors from the slot and returns the
slot to the worker's free-list — backpressure and zero pickling for the
array payload.

Fork-safety: workers NEVER touch jax — the default collate runs a
numpy-only twin (``_np_collate``), and Tensor leaves from custom collates
are unwrapped to numpy before transport. (A forked child driving the
parent's TPU client/tunnel would be undefined behavior, same reason the
reference forbids CUDA in workers.)

Batch order is deterministic: batch i is assigned to worker ``i % W`` and
each worker preserves its own order, so the parent drains workers
round-robin — the reference's ordered reacquisition without the reorder
buffer.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from multiprocessing import shared_memory

import numpy as np

__all__ = ["MultiProcessLoaderIter"]


class _ArrRef:
    """Skeleton placeholder for an array leaf moved through shared memory."""

    __slots__ = ("idx", "kind")

    def __init__(self, idx, kind):
        self.idx = idx
        self.kind = kind  # "tensor" -> rewrap as Tensor in the parent


def _tensor_to_np(t):
    """Unwrap a Tensor in a WORKER process. Host(cpu)-backed values are a
    metadata-free numpy view; an accelerator-committed buffer would have to
    round-trip the parent's device client from a forked child — undefined
    behavior, so refuse loudly (the reference similarly forbids CUDA
    tensors in loader workers)."""
    v = t._value
    try:
        devs = {d.platform for d in v.devices()}
    except Exception:
        devs = {"cpu"}
    if devs - {"cpu"}:
        raise RuntimeError(
            "DataLoader worker received an accelerator-backed Tensor "
            f"(devices {sorted(devs)}); datasets/collate_fns used with "
            "num_workers>0 must return numpy arrays or host tensors")
    return np.asarray(v)


def _encode(obj, arrays):
    from ..core.tensor import Tensor

    if isinstance(obj, Tensor):
        arrays.append(_tensor_to_np(obj))
        return _ArrRef(len(arrays) - 1, "tensor")
    if isinstance(obj, np.ndarray):
        arrays.append(np.ascontiguousarray(obj))
        return _ArrRef(len(arrays) - 1, "ndarray")
    if isinstance(obj, tuple):
        return tuple(_encode(o, arrays) for o in obj)
    if isinstance(obj, list):
        return [_encode(o, arrays) for o in obj]
    if isinstance(obj, dict):
        return {k: _encode(v, arrays) for k, v in obj.items()}
    return obj


def _decode(obj, arrays):
    from ..core.tensor import Tensor

    if isinstance(obj, _ArrRef):
        arr = arrays[obj.idx]
        return Tensor(arr) if obj.kind == "tensor" else arr
    if isinstance(obj, tuple):
        return tuple(_decode(o, arrays) for o in obj)
    if isinstance(obj, list):
        return [_decode(o, arrays) for o in obj]
    if isinstance(obj, dict):
        return {k: _decode(v, arrays) for k, v in obj.items()}
    return obj


def _np_collate(batch):
    """Numpy-only twin of default_collate_fn (workers must not build
    Tensors: jax in a forked child would drive the parent's device client).
    Leaves are marked "tensor" so the parent rewraps them."""
    from ..core.tensor import Tensor

    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([_tensor_to_np(s) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (tuple, list)):
        return [_np_collate(list(items)) for items in zip(*batch)]
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    return batch


def _mark_all_tensor(obj):
    """Skeleton post-pass for the default-collate path: every array leaf
    becomes a Tensor in the parent (default_collate_fn's contract)."""
    if isinstance(obj, _ArrRef):
        return _ArrRef(obj.idx, "tensor")
    if isinstance(obj, tuple):
        return tuple(_mark_all_tensor(o) for o in obj)
    if isinstance(obj, list):
        return [_mark_all_tensor(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _mark_all_tensor(v) for k, v in obj.items()}
    return obj


class _Slot:
    """One reusable shared-memory segment; grows (unlink + recreate) when a
    batch outgrows it. The parent attaches by the name sent per batch, so
    regrowth is transparent."""

    def __init__(self, wid, idx, size=1 << 20):
        self.idx = idx
        self.gen = 0
        self.wid = wid
        self.shm = shared_memory.SharedMemory(
            create=True, size=size, name=self._name())

    def _name(self):
        return f"pdtpu_{os.getpid()}_{self.wid}_{self.idx}_{self.gen}"

    def ensure(self, nbytes):
        if self.shm.size >= nbytes:
            return
        self.shm.close()
        self.shm.unlink()
        self.gen += 1
        self.shm = shared_memory.SharedMemory(
            create=True, size=max(nbytes, 2 * self.shm.size),
            name=self._name())

    def write(self, arrays):
        specs = []
        off = 0
        total = sum(a.nbytes for a in arrays)
        self.ensure(total)
        for a in arrays:
            # write in place: one copy into the segment (tobytes() would
            # materialize a transient duplicate of every batch)
            dst = np.ndarray(a.shape, a.dtype, buffer=self.shm.buf,
                             offset=off)
            np.copyto(dst, a)
            specs.append((tuple(a.shape), a.dtype.str, off))
            off += a.nbytes
        return self.shm.name, specs

    def destroy(self):
        try:
            self.shm.close()
            self.shm.unlink()
        except Exception:
            pass


def _worker_loop(loader_state, wid, index_q, result_q, free_q, n_slots):
    """Worker process main: collate assigned batches into the slot ring."""
    (dataset, collate, use_np_collate, worker_init_fn, num_workers,
     iterable, batch_size, drop_last) = loader_state
    from . import _WorkerInfo
    import paddle_tpu.io as _io

    _io._worker_info = _WorkerInfo(id=wid, num_workers=num_workers,
                                   dataset=dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    slots = [_Slot(wid, i) for i in range(n_slots)]
    for s in slots:
        free_q.put(s.idx)

    def send(bid, data):
        arrays = []
        skeleton = _encode(data, arrays)
        if use_np_collate:
            skeleton = _mark_all_tensor(skeleton)
        slot_idx = free_q.get()  # backpressure: waits for the parent
        name, specs = slots[slot_idx].write(arrays)
        result_q.put(("ok", bid, slot_idx, name, skeleton, specs))

    try:
        if iterable:
            bid = 0
            batch = []
            for item in dataset:
                batch.append(item)
                if batch_size is not None and len(batch) == batch_size:
                    send(bid, collate(batch))
                    bid += 1
                    batch = []
            if batch and not drop_last:
                send(bid, collate(batch))
            result_q.put(("end", None, None, None, None, None))
        else:
            # epoch-framed protocol: (bid, idxs) work items, "epoch_end"
            # markers (worker echoes an "end" so the parent can frame
            # epochs — this is what makes persistent_workers possible),
            # None = shutdown
            while True:
                item = index_q.get()
                if item is None:
                    break
                if item == "epoch_end":
                    result_q.put(("end", None, None, None, None, None))
                    continue
                bid, idxs = item
                send(bid, collate([dataset[i] for i in idxs]))
    except Exception:
        result_q.put(("err", traceback.format_exc(), None, None, None, None))
    finally:
        # segments must outlive the last in-flight batch: wait until the
        # parent has returned every slot (it returns one per copied batch),
        # then unlink. A 10s cap covers an abandoning parent; terminated
        # workers leave cleanup to the resource tracker.
        reclaimed = 0
        try:
            while reclaimed < n_slots:
                free_q.get(timeout=10)
                reclaimed += 1
        except Exception:
            pass
        for s in slots:
            s.destroy()


class MultiProcessLoaderIter:
    """Parent-side iterator over a fleet of worker processes."""

    def __init__(self, loader):
        from . import default_collate_fn

        self._loader = loader
        self._W = loader.num_workers
        ctx = mp.get_context("fork")
        self._workers = []
        self._index_qs = []
        self._result_qs = []
        self._free_qs = []
        self._slot_names: dict[tuple[int, int], str] = {}
        use_np = loader.collate_fn is default_collate_fn
        collate = _np_collate if use_np else loader.collate_fn
        n_slots = max(2, loader.prefetch_factor)
        self._iterable = loader.iterable_mode

        self._persistent = (getattr(loader, "persistent_workers", False)
                            and not self._iterable)
        self._total = None

        state = (loader.dataset, collate, use_np,
                 getattr(loader, "worker_init_fn", None), self._W,
                 self._iterable, loader.batch_size, loader.drop_last)
        for w in range(self._W):
            iq = ctx.Queue()
            rq = ctx.Queue()
            fq = ctx.Queue()
            p = ctx.Process(
                target=_worker_loop,
                args=(state, w, iq, rq, fq, n_slots), daemon=True)
            p.start()
            self._workers.append(p)
            self._index_qs.append(iq)
            self._result_qs.append(rq)
            self._free_qs.append(fq)

    def _feed_epoch(self):
        """Assign this epoch's batches round-robin (deterministic global
        order) and close the epoch with per-worker markers. Re-listing the
        sampler each epoch keeps shuffle-per-epoch semantics."""
        batches = list(self._loader.batch_sampler)
        self._total = len(batches)
        for bid, idxs in enumerate(batches):
            self._index_qs[bid % self._W].put((bid, idxs))
        for iq in self._index_qs:
            iq.put("epoch_end")

    def alive(self):
        return bool(self._workers) and all(p.is_alive()
                                           for p in self._workers)

    @staticmethod
    def _read_segment(name, end):
        """Copy `end` bytes out of the named shared-memory segment. Linux
        exposes segments under /dev/shm (direct read avoids 3.12's
        resource-tracker double-registration on attach); other POSIX systems
        fall back to a SharedMemory attach with tracking suppressed."""
        try:
            with open(f"/dev/shm/{name}", "rb") as f:
                return f.read(end)
        except FileNotFoundError:
            from multiprocessing import shared_memory

            try:
                seg = shared_memory.SharedMemory(name=name, track=False)
            except TypeError:  # <3.13: no track kwarg; unregister manually
                seg = shared_memory.SharedMemory(name=name)
                try:
                    from multiprocessing import resource_tracker

                    resource_tracker.unregister(seg._name, "shared_memory")
                except Exception:
                    pass
            try:
                return bytes(seg.buf[:end])
            finally:
                seg.close()

    #: timeout=0 (paddle's "no timeout") maps to this cap instead of blocking
    #: forever: the fleet is fork-started from a multithreaded JAX parent, and
    #: a child that forked while another thread held a lock (malloc/numpy/
    #: logging) can wedge silently — a bounded get turns that hang into a
    #: diagnosable error.
    DEFAULT_READ_TIMEOUT = 600.0

    def _read_one(self, w):
        import queue as _queue

        timeout = (getattr(self._loader, "timeout", 0)
                   or self.DEFAULT_READ_TIMEOUT)
        try:
            msg = self._result_qs[w].get(timeout=timeout)
        except _queue.Empty:
            self.close()
            raise RuntimeError(
                f"DataLoader worker {w} timed out after {timeout}s (stuck "
                "__getitem__/collate_fn, or a fork-while-threaded deadlock "
                "— set DataLoader(timeout=...) to tune the cap)") from None
        kind = msg[0]
        if kind == "err":
            self.close()
            raise RuntimeError(
                f"DataLoader worker {w} failed:\n{msg[1]}")
        if kind == "end":
            return None
        _, bid, slot_idx, name, skeleton, specs = msg
        self._slot_names[(w, slot_idx)] = name
        # read the segment file directly instead of SharedMemory(name=...):
        # the parent copies the bytes out anyway, and 3.12's attach path
        # would register the segment with the shared resource tracker,
        # producing unlink-race warnings against the owning worker
        end = max((off + int(np.prod(shape or (1,))) * np.dtype(dt).itemsize)
                  for shape, dt, off in specs) if specs else 0
        raw = self._read_segment(name, end)
        arrays = []
        for shape, dtype, off in specs:
            n = int(np.prod(shape)) if shape else 1
            a = np.frombuffer(raw, dtype=np.dtype(dtype), count=n,
                              offset=off).reshape(shape).copy()
            arrays.append(a)
        self._free_qs[w].put(slot_idx)  # ring slot back to the worker
        return _decode(skeleton, arrays)

    def __iter__(self):
        completed = False
        try:
            if self._iterable:
                live = list(range(self._W))
                while live:
                    for w in list(live):
                        out = self._read_one(w)
                        if out is None:
                            live.remove(w)
                        else:
                            yield out
            else:
                self._feed_epoch()
                for bid in range(self._total):
                    out = self._read_one(bid % self._W)
                    if out is None:  # worker ended early: internal error
                        raise RuntimeError(
                            "DataLoader worker ended before its batches")
                    yield out
                # drain the per-worker epoch markers so the NEXT epoch's
                # reads start framed
                for w in range(self._W):
                    if self._read_one(w) is not None:
                        raise RuntimeError(
                            "DataLoader worker/epoch desynchronization")
                completed = True
        finally:
            # persistent workers survive a CLEANLY completed epoch; an
            # abandoned iteration leaves batches in flight, so the fleet is
            # torn down either way to avoid desync
            if not (self._persistent and completed):
                self.close()

    def close(self):
        for p, iq in zip(self._workers, self._index_qs):
            try:
                iq.put_nowait(None)
            except Exception:
                pass
        dirty = set()
        for w, p in enumerate(self._workers):
            p.join(timeout=2)
            if p.is_alive():
                p.terminate()
                dirty.add(w)
            elif p.exitcode not in (0, None):
                dirty.add(w)
        # a cleanly-exited worker unlinked its own slots; only sweep up
        # after terminated/crashed workers (double-unlink trips the
        # resource tracker's warnings)
        for (w, _), name in self._slot_names.items():
            if w not in dirty:
                continue
            try:
                shm = shared_memory.SharedMemory(name=name)
                shm.close()
                shm.unlink()
            except Exception:
                pass
        self._workers = []
