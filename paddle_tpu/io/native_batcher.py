"""Native batch assembly: the C++ prefetch core behind DataLoader for
contiguous-array datasets (the reference's C++ buffered-reader role)."""
from __future__ import annotations

import ctypes

import numpy as np

from ..core import native

__all__ = ["NativeBatcher", "supported"]


def supported() -> bool:
    return native.available()


class NativeBatcher:
    """Iterate index-gathered batches of several aligned numpy arrays, with
    assembly running in a C++ worker thread (outside the GIL)."""

    def __init__(self, arrays, indices, batch_size, drop_last=False,
                 prefetch=2):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        # keep C-contiguous copies alive for the batcher's lifetime
        self._arrays = [np.ascontiguousarray(a) for a in arrays]
        self._indices = np.ascontiguousarray(np.asarray(indices, np.int64))
        if len(self._indices):
            lo, hi = int(self._indices.min()), int(self._indices.max())
            if lo < 0:
                raise ValueError(
                    "native batcher requires non-negative indices "
                    "(python-style negative indexing is a DataLoader-"
                    "fallback feature)")
            for a in self._arrays:
                if a.shape[0] <= hi:
                    raise ValueError("index out of range for source array")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self._h = lib.bt_create(self.batch_size, int(drop_last), int(prefetch))
        for a in self._arrays:
            row_bytes = a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
            lib.bt_add_source(
                self._h, a.ctypes.data_as(ctypes.c_char_p), row_bytes)
        lib.bt_start(
            self._h, self._indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(self._indices))
        self._remaining = lib.bt_num_batches(self._h)

    def __len__(self):
        return int(self._lib.bt_num_batches(self._h))

    def __iter__(self):
        return self

    def __next__(self):
        if self._h is None or self._remaining <= 0:
            self.close()
            raise StopIteration
        outs = []
        ptrs = (ctypes.c_char_p * len(self._arrays))()
        for i, a in enumerate(self._arrays):
            buf = np.empty((self.batch_size,) + a.shape[1:], a.dtype)
            outs.append(buf)
            ptrs[i] = ctypes.cast(buf.ctypes.data, ctypes.c_char_p)
        count = self._lib.bt_next(self._h, ptrs, len(outs))
        if count == 0:
            self.close()
            raise StopIteration
        self._remaining -= 1
        if count < self.batch_size:
            outs = [o[:count] for o in outs]
        return outs

    def close(self):
        if self._h is not None:
            self._lib.bt_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
