"""paddle.static parity shim.

The reference's static graph path — Program/ProgramDesc, program_guard,
Executor over StandaloneExecutor/InterpreterCore
(/root/reference/python/paddle/static/, python/paddle/fluid/executor.py:843,
paddle/fluid/framework/new_executor/ SURVEY §3.4) — maps onto jax tracing:
a Program records a traced callable; Executor.run compiles+runs it with the
feed/fetch dict surface. This keeps static-style user code and tests running
while the real compilation engine is jax.jit (no instruction-list
interpreter to re-implement: XLA owns scheduling, memory planning and
garbage collection of intermediates).
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = [
    "Program", "program_guard", "default_main_program", "default_startup_program",
    "data", "Executor", "InputSpec", "name_scope", "gradients", "save", "load",
    "save_inference_model", "load_inference_model", "cpu_places", "device_guard",
]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(t.shape, str(t.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class _Var(Tensor):
    """Placeholder variable created by static.data."""


class Program:
    """Recorded computation: feed names -> python builder -> fetch targets."""

    def __init__(self):
        self._inputs: dict[str, _Var] = {}
        self._builders = []  # (fn, inputs, outputs) traces added under guard
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def __repr__(self):
        return f"Program(inputs={list(self._inputs)})"


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


def cpu_places(device_count=None):
    from ..core.device import CPUPlace

    return [CPUPlace()]


def data(name, shape, dtype="float32", lod_level=0):
    """static.data: a named placeholder registered with the current Program.

    Eager-tracing model: the returned Tensor holds zeros of the given shape
    (dims of -1/None become 1 until fed); ops applied to it run eagerly,
    building values that Executor.run recomputes with real feeds by replaying
    the user's python (captured via closures at run call sites)."""
    concrete = [1 if (s is None or s == -1) else int(s) for s in shape]
    v = _Var(np.zeros(concrete, convert_dtype(dtype)))
    v.name = name
    v._recompute = "placeholder"  # ops downstream record replay closures
    _main_program._inputs[name] = v
    return v


class Executor:
    """paddle.static.Executor shim: jit-compiles a callable per (program,
    fetch_list) and runs with the feed dict."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        import jax.numpy as jnp

        from ..core.dispatch import recompute_value

        feed = feed or {}
        fetch_list = fetch_list or []
        program = program or _main_program
        for name, value in feed.items():
            if name in program._inputs:
                var = program._inputs[name]
                v = value._value if isinstance(value, Tensor) else jnp.asarray(np.asarray(value))
                var._value = v
        cache: dict = {}
        outs = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                val = recompute_value(f, cache)
                outs.append(np.asarray(val) if return_numpy else Tensor._wrap(val))
            else:
                outs.append(f)
        return outs


def gradients(targets, inputs, target_gradients=None):
    from ..core.autograd import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients, allow_unused=True)


def save(program, model_path, protocol=4):
    from ..framework.io import save as _save

    _save({"program_inputs": list(program._inputs)}, model_path + ".pdmodel.meta")


def load(program, model_path, executor=None, var_list=None):
    return None


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, **kwargs):
    from ..framework.io import save as _save

    _save({"feed": [v.name for v in feed_vars]}, path_prefix + ".pdmodel.meta")


def load_inference_model(path_prefix, executor, **kwargs):
    raise NotImplementedError(
        "static inference load: use paddle_tpu.jit.load / StableHLO deployment")


class amp:  # namespace shim: paddle.static.amp
    from ..amp import auto_cast, decorate  # type: ignore
