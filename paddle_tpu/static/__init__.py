"""paddle.static parity: a real compiled static-graph path.

The reference's static mode is a compiled, serializable program: user code
builds a ProgramDesc under ``program_guard``
(/root/reference/python/paddle/static/), ``Executor.run`` compiles it once
per (program, feed-signature) through an executor cache
(python/paddle/fluid/executor.py:843 ``Executor.run`` -> ``_ExecutorCache``
:666) and executes via the C++ StandaloneExecutor/InterpreterCore
(paddle/fluid/framework/new_executor/standalone_executor.h:34); programs and
parameters serialize to *.pdmodel/*.pdiparams
(paddle/fluid/framework/program_desc.h:32, framework.proto).

TPU-native mapping:

- Graph capture: ops applied to ``static.data`` placeholders record replay
  closures (core/dispatch.py:_maybe_attach_recompute) — the ProgramDesc role.
- ``Executor.run`` traces the replay ONCE per (program, feed names, feed
  shapes/dtypes, fetch set) into a pure function and ``jax.jit``-compiles it;
  subsequent runs hit the compiled cache with zero re-tracing (the
  _ExecutorCache + InterpreterCore role — XLA owns instruction scheduling,
  memory planning and garbage collection of intermediates).
- ``Scope``/``Variable`` hold named parameter state outside the graph
  (paddle/fluid/framework/scope.h:49); parameters enter the compiled program
  as traced inputs so ``static.load`` updates take effect without retracing.
- ``save_inference_model``/``load_inference_model`` serialize the
  feed->fetch slice as a jax.export (StableHLO) archive + weights, loadable
  in a fresh process WITHOUT the builder's python
  (paddle/fluid/inference/io.cc save_inference_model).
"""
from __future__ import annotations

import contextlib
import pickle

import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

__all__ = [
    "Program", "program_guard", "default_main_program", "default_startup_program",
    "data", "Executor", "InputSpec", "name_scope", "gradients", "save", "load",
    "save_inference_model", "load_inference_model", "cpu_places", "device_guard",
    "Scope", "Variable", "global_scope", "scope_guard", "create_parameter",
    "InferenceProgram",
]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(t.shape, str(t.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class _Var(Tensor):
    """Placeholder variable created by static.data / create_parameter."""


class Variable:
    """Named value slot in a Scope (reference paddle/fluid/framework/variable.h)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def get_tensor(self):
        return self._value

    def set(self, value, place=None):
        self._value = np.asarray(value)


class Scope:
    """Name->Variable tree with parent lookup (reference scope.h:49):
    ``var`` finds-or-creates locally, ``find_var`` walks to the root,
    ``new_scope`` opens a child whose lookups fall through to this scope."""

    def __init__(self, parent=None):
        self._vars: dict[str, Variable] = {}
        self._parent = parent
        self._kids: list[Scope] = []

    def var(self, name) -> Variable:
        v = self._vars.get(name)
        if v is None:
            v = Variable(name)
            self._vars[name] = v
        return v

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return None

    def new_scope(self) -> "Scope":
        k = Scope(self)
        self._kids.append(k)
        return k

    def local_var_names(self):
        return list(self._vars)

    def drop_kids(self):
        self._kids.clear()


_global_scope = Scope()
_param_uid = 0


def global_scope() -> Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = prev


class Program:
    """Recorded computation: feed placeholders + parameters -> replay graph.

    The ProgramDesc analogue (program_desc.h:32): holds the named inputs and
    parameters whose replay closures (recorded by op dispatch during the
    build under ``program_guard``) constitute the op graph. Serialization of
    a feed->fetch slice is ``save_inference_model`` (jax.export archive)."""

    def __init__(self):
        self._inputs: dict[str, _Var] = {}
        self._params: dict[str, _Var] = {}
        self.random_seed = 0

    def global_block(self):
        return self

    def all_parameters(self):
        return list(self._params.values())

    def clone(self, for_test=False):
        return self

    def __repr__(self):
        return (f"Program(inputs={list(self._inputs)}, "
                f"params={list(self._params)})")


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev_m, prev_s


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    yield


def cpu_places(device_count=None):
    from ..core.device import CPUPlace

    return [CPUPlace()]


def data(name, shape, dtype="float32", lod_level=0):
    """static.data: a named placeholder registered with the current Program.

    Build-time tracing model: the returned Tensor holds zeros of the given
    shape (dims of -1/None become 1 until fed) so ops applied to it execute
    eagerly while recording replay closures; ``Executor.run`` traces those
    closures with real feeds into a compiled program. The declared shape
    (with None preserved) drives shape-polymorphic export."""
    declared = tuple(shape)
    concrete = [1 if (s is None or s == -1) else int(s) for s in shape]
    v = _Var(np.zeros(concrete, convert_dtype(dtype)))
    v.name = name
    v._recompute = "placeholder"  # ops downstream record replay closures
    v._declared_shape = declared
    _main_program._inputs[name] = v
    return v


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """static.create_parameter: a trainable variable registered with the
    current Program and living in the global Scope (reference
    python/paddle/static/nn/common.py). It enters compiled programs as a
    traced input, so updating the Scope (e.g. ``static.load``) changes what
    subsequent ``Executor.run`` calls compute without retracing."""
    if name is None:
        # process-global counter: default-named params from different
        # Programs share the global Scope and must not collide
        global _param_uid
        name = f"param_{_param_uid}"
        _param_uid += 1
    shape = tuple(int(s) for s in shape)
    np_dtype = convert_dtype(dtype)
    if default_initializer is not None:
        init = np.asarray(default_initializer(shape), np_dtype)
    elif is_bias or not np.issubdtype(np.dtype(np_dtype), np.floating):
        init = np.zeros(shape, np_dtype)
    else:
        from ..framework.random import np_rng

        fan_in = shape[0] if shape else 1
        fan_out = shape[-1] if len(shape) > 1 else 1
        limit = float(np.sqrt(6.0 / max(fan_in + fan_out, 1)))
        init = np_rng().uniform(-limit, limit, shape).astype(np_dtype)
    v = _Var(init)
    v.name = name
    v.stop_gradient = False
    v._recompute = "placeholder"
    v._declared_shape = shape
    _main_program._params[name] = v
    global_scope().var(name).set(init)
    return v


class _FetchTarget:
    """Opaque fetch token returned by load_inference_model (the reference's
    fetch_targets variables)."""

    def __init__(self, name, index):
        self.name = name
        self.index = index

    def __repr__(self):
        return f"FetchTarget({self.name})"


class InferenceProgram:
    """A deserialized feed->fetch program: executes the jax.export artifact
    with saved weights — the AnalysisPredictor's loaded-program role. Run it
    through ``Executor.run`` exactly like a built Program."""

    def __init__(self, exported, params, feed_names, fetch_names):
        self._exported = exported
        self._params = params
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)

    def program_text(self):
        return self._exported.mlir_module()

    def _run(self, feed, fetch_list, return_numpy):
        args = [np.asarray(feed[n]) for n in self.feed_names]
        outs = self._exported.call(self._params, *args)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        by_name = dict(zip(self.fetch_names, outs))
        sel = []
        for f in fetch_list or [_FetchTarget(n, i) for i, n in enumerate(self.fetch_names)]:
            name = f.name if isinstance(f, _FetchTarget) else f
            val = by_name[name]
            sel.append(np.asarray(val) if return_numpy else Tensor._wrap(val))
        return sel


_EXEC_METRICS = None


def _exec_metrics():
    """Executor-cache counters (lazy: static is importable without forcing
    the telemetry registry up mid-package-init). Hits/misses were
    previously visible only through the ``_trace_count`` test hook; now
    they are scrapeable and land in ``tools/metrics_dump.py``."""
    global _EXEC_METRICS
    if _EXEC_METRICS is None:
        from .. import telemetry

        reg = telemetry.registry()
        _EXEC_METRICS = (
            reg.counter("static_executor_cache_hits_total",
                        "Executor.run served from the compiled-trace cache"),
            reg.counter("static_executor_cache_misses_total",
                        "Executor.run (re)compiles (cache miss or cache "
                        "bypassed)"),
        )
    return _EXEC_METRICS


class Executor:
    """paddle.static.Executor: compiles the program's replay graph once per
    (program, feed names, feed signature, fetch set) and caches the compiled
    callable — the reference's ``Executor.run`` -> ``_ExecutorCache`` ->
    StandaloneExecutor pipeline (executor.py:843,666). ``_trace_count``
    increments only when a cache entry traces, so tests can prove the second
    run executes the compiled program without re-tracing; the same events
    are exported as ``static_executor_cache_{hits,misses}_total`` metrics,
    and every compile reports its feed signature to the
    ``telemetry.perf.CompileWatcher`` (callable ``static.Executor``), so a
    feed whose shape churns across runs shows up as a recompilation storm
    with the offending feed named by ``explain_recompile()``."""

    def __init__(self, place=None):
        self.place = place
        self._cache: dict = {}
        self._trace_count = 0

    def close(self):
        self._cache.clear()

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        import jax.numpy as jnp

        program = program or _main_program
        feed = feed or {}
        fetch_list = list(fetch_list) if fetch_list is not None else []
        if isinstance(program, InferenceProgram):
            return program._run(feed, fetch_list, return_numpy)
        scope = scope or global_scope()

        unknown = sorted(set(feed) - set(program._inputs))
        if unknown:
            raise ValueError(
                f"Executor.run: feed name(s) {unknown} are not placeholders "
                f"of this program (has: {sorted(program._inputs)}) — "
                "the reference raises on unknown feed variables too")
        # trace over ALL placeholders (fed ones with fed shapes, others with
        # their build-time shapes) so nothing is ever baked in as a stale
        # constant; after tracing, fetches that actually USE an unfed
        # placeholder raise below
        arrays = {}
        for n, var in program._inputs.items():
            if n in feed:
                v = feed[n]
                a = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                arrays[n] = a
                var._value = a  # keep build-time vars inspectable
            else:
                arrays[n] = jnp.asarray(var._value)
        feed_names = sorted(program._inputs)

        param_names = sorted(program._params)
        param_vals = []
        for n in param_names:
            var = scope.find_var(n)
            if var is not None and var._value is not None:
                param_vals.append(jnp.asarray(var._value))
            else:
                param_vals.append(program._params[n]._value)

        fetch_ts = [f for f in fetch_list if isinstance(f, Tensor)]
        key = (
            id(program),
            tuple(feed_names),
            tuple((tuple(arrays[n].shape), str(arrays[n].dtype)) for n in feed_names),
            tuple(id(f) for f in fetch_ts),
        )
        import time as _time

        from ..telemetry import perf as _perf

        entry = self._cache.get(key) if use_program_cache else None
        compiled = entry is None
        trace_s = 0.0
        if entry is None:
            _exec_metrics()[1].inc()
            _t0 = _time.monotonic()
            entry = self._compile(program, feed_names, param_names, fetch_ts,
                                  tuple(arrays[n] for n in feed_names),
                                  tuple(param_vals))
            trace_s = _time.monotonic() - _t0
            if use_program_cache:
                self._cache[key] = entry
        else:
            _exec_metrics()[0].inc()

        jitted, needed = entry
        missing = sorted(n for n in needed if n not in feed)
        if missing:
            raise ValueError(
                f"Executor.run: fetch targets depend on placeholder(s) "
                f"{missing} which are not in the feed")
        _t0 = _time.monotonic()
        out_vals = jitted(
            tuple(arrays[n] for n in feed_names), tuple(param_vals))
        # the compile watcher sees one signature per (feed shapes/dtypes);
        # wall time = trace + first jitted call (which pays backend compile)
        _perf.compile_watcher().record_call(
            "static.Executor",
            tuple((n, tuple(arrays[n].shape), str(arrays[n].dtype))
                  for n in feed_names),
            wall_s=(trace_s + _time.monotonic() - _t0) if compiled else None)
        out_map = {id(t): v for t, v in zip(fetch_ts, out_vals)}
        outs = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                val = out_map[id(f)]
                outs.append(np.asarray(val) if return_numpy else Tensor._wrap(val))
            else:
                outs.append(f)
        return outs

    def _compile(self, program, feed_names, param_names, fetch_ts,
                 feed_vals, param_vals):
        import jax

        from ..core.dispatch import recompute_value

        placeholders = [program._inputs[n] for n in feed_names]
        params = [program._params[n] for n in param_names]
        # one increment per (program, signature, fetch-set) compile; cache
        # hits in run() never reach here — the observable no-retrace proof
        self._trace_count += 1

        def pure(feed_vals, param_vals):
            cache = {id(p): v for p, v in zip(placeholders, feed_vals)}
            cache.update({id(p): v for p, v in zip(params, param_vals)})
            # gradients() replays need to distinguish graph seeds from
            # memoized intermediates (which must NOT leak into jax.grad)
            cache["__seed_ids__"] = frozenset(cache)
            # control-flow replays (static.nn.cond/while_loop) re-invoke the
            # user's builder closures, which read placeholder ._value —
            # swap the traced values in for the duration of the trace
            old = [(p, p._value) for p in placeholders + params]
            for p, v in zip(placeholders, feed_vals):
                p._value = v
            for p, v in zip(params, param_vals):
                p._value = v
            try:
                return tuple(recompute_value(f, cache) for f in fetch_ts)
            finally:
                for p, v in old:
                    p._value = v

        # which placeholders do the fetches actually consume? (the
        # reference prunes the program to the fetch deps; unfed-but-needed
        # variables raise rather than silently using stale constants)
        from jax.extend.core import Var as _JVar

        # NOTE: this does not double-trace — measured on this jax version
        # (a side-effect counter in `pure` fires once across make_jaxpr +
        # the first jit call), the jit below reuses the cached trace
        jaxpr = jax.make_jaxpr(pure)(feed_vals, param_vals)
        used = set()
        for eqn in jaxpr.jaxpr.eqns:
            used.update(v for v in eqn.invars if isinstance(v, _JVar))
        used.update(v for v in jaxpr.jaxpr.outvars
                    if isinstance(v, _JVar))
        n_feed = len(feed_names)
        needed = {feed_names[i]
                  for i, v in enumerate(jaxpr.jaxpr.invars[:n_feed])
                  if v in used}
        return jax.jit(pure), needed


def gradients(targets, inputs, target_gradients=None):
    """static.gradients: symbolic gradients recorded INTO the program's
    replay graph (the reference's append_backward role,
    python/paddle/fluid/backward.py) — fetching them through ``Executor.run``
    differentiates the compiled program at the fed values, not the
    build-time constants."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import recompute_value

    tlist = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    ilist = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is None:
        glist = [None] * len(tlist)
    else:
        glist = (list(target_gradients)
                 if isinstance(target_gradients, (list, tuple))
                 else [target_gradients])

    outs: list[Tensor] = []

    def replay(cache):
        if outs and id(outs[0]) in cache:
            return [cache[id(o)] for o in outs]
        in_vals = [recompute_value(i, cache) for i in ilist]

        def f(ivals):
            # rebuild from graph seeds only: memoized intermediates in the
            # outer cache were computed from the ORIGINAL input values and
            # would make the differentiated targets constants
            seed_ids = cache.get("__seed_ids__", frozenset())
            c2 = {k: cache[k] for k in seed_ids}
            c2["__seed_ids__"] = seed_ids
            for i, v in zip(ilist, ivals):
                c2[id(i)] = v
            total = None
            for t, g in zip(tlist, glist):
                tv = recompute_value(t, c2)
                if g is not None:
                    # graph tensors replay with fed values; raw arrays are
                    # genuine constants
                    gv = (recompute_value(g, c2) if isinstance(g, Tensor)
                          else jnp.asarray(np.asarray(g)))
                    term = jnp.sum(tv * gv)
                else:
                    term = jnp.sum(tv)
                total = term if total is None else total + term
            return total

        gvals = list(jax.grad(f)(in_vals))
        for o, g in zip(outs, gvals):
            cache[id(o)] = g
        return gvals

    build_vals = replay({})
    for idx, v in enumerate(build_vals):
        gt = Tensor._wrap(v)
        gt._recompute = (replay, idx)
        outs.append(gt)
    return outs


def save(program, model_path, protocol=4):
    """static.save: persist the program's parameters from the Scope —
    the reference's paddle.static.save -> <path>.pdparams
    (python/paddle/static/io.py save)."""
    state = {}
    scope = global_scope()
    for n, p in program._params.items():
        var = scope.find_var(n)
        val = var._value if var is not None and var._value is not None else p._value
        state[n] = np.asarray(val)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """static.load: restore parameters into the Scope (and the program's
    build-time values). Compiled executor cache entries stay valid: params
    are traced inputs, so the next run just sees the new values."""
    import jax.numpy as jnp

    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    keep = None
    if var_list is not None:
        keep = {getattr(v, "name", v) for v in var_list}
    scope = global_scope()
    for n, v in state.items():
        if keep is not None and n not in keep:
            continue
        scope.var(n).set(v)
        if n in program._params:
            program._params[n]._value = jnp.asarray(v)
    return state


def _feed_struct(var, sym_count):
    """Declared placeholder shape -> ShapeDtypeStruct; None/-1 dims export as
    symbolic dimensions so the artifact is shape-polymorphic."""
    import jax
    from jax import export as jexport

    declared = getattr(var, "_declared_shape", None) or tuple(var.shape)
    dims = []
    for s in declared:
        if s in (None, -1):
            (d,) = jexport.symbolic_shape(f"_pd_s{next(sym_count)}")
            dims.append(d)
        else:
            dims.append(int(s))
    return jax.ShapeDtypeStruct(tuple(dims), np.dtype(var._value.dtype))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Serialize the feed->fetch slice of the program as a jax.export
    (StableHLO) archive + weights: the reference's *.pdmodel ProgramDesc +
    *.pdiparams pair (paddle/fluid/inference/io.cc, python/paddle/static/io.py
    save_inference_model). Loads in a fresh process without builder python."""
    import itertools
    import os

    import jax
    from jax import export as jexport

    from ..core.dispatch import recompute_value

    program = program or _main_program
    feed_vars = list(feed_vars) if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = list(fetch_vars) if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    scope = global_scope()

    param_names = sorted(program._params)
    param_vals = {}
    for n in param_names:
        var = scope.find_var(n)
        val = var._value if var is not None and var._value is not None else program._params[n]._value
        param_vals[n] = np.asarray(val)

    def pure(params, *feed_vals):
        cache = {id(p): v for p, v in zip(feed_vars, feed_vals)}
        cache.update({id(program._params[n]): params[n] for n in param_names})
        cache["__seed_ids__"] = frozenset(cache)
        return tuple(recompute_value(f, cache) for f in fetch_vars)

    sym_count = itertools.count()
    structs = [_feed_struct(v, sym_count) for v in feed_vars]
    p_structs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for n, v in param_vals.items()}
    exported = jexport.export(jax.jit(pure), platforms=("cpu", "tpu"))(
        p_structs, *structs)

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    feed_names = [getattr(v, "name", f"feed_{i}") for i, v in enumerate(feed_vars)]
    fetch_names = [f"fetch_{i}" for i in range(len(fetch_vars))]
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({"params": param_vals, "feed_names": feed_names,
                     "fetch_names": fetch_names}, f)


def load_inference_model(path_prefix, executor, **kwargs):
    """Deserialize a save_inference_model artifact; returns
    ``[InferenceProgram, feed_names, fetch_targets]`` runnable through
    ``Executor.run`` (reference python/paddle/static/io.py
    load_inference_model)."""
    from jax import export as jexport

    with open(path_prefix + ".pdiparams", "rb") as f:
        blob = pickle.load(f)
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    prog = InferenceProgram(exported, blob["params"], blob["feed_names"],
                            blob["fetch_names"])
    fetch_targets = [_FetchTarget(n, i) for i, n in enumerate(blob["fetch_names"])]
    return [prog, list(blob["feed_names"]), fetch_targets]


class amp:  # namespace shim: paddle.static.amp
    from ..amp import auto_cast, decorate  # type: ignore


from . import nn  # noqa: E402,F401  (static.nn builder + control-flow ops)
