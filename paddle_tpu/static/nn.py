"""paddle.static.nn — static-graph layer builders + control-flow ops
(reference /root/reference/python/paddle/static/nn/__init__.py: fc, conv2d,
batch_norm, embedding, ... and control_flow.py: cond/case/switch_case/
while_loop).

Builders create parameters with ``static.create_parameter`` (registered in
the current Program + global Scope) and apply the SAME functional ops the
dygraph layers use — the ops record replay closures on the placeholder
graph, so ``Executor.run`` compiles them like any other static op.

Control flow delegates to the dy2static conversion runtime: on concrete
values python semantics hold; on traced values (inside a compiled program)
``lax.cond``/``lax.while_loop`` are emitted — the role of the reference's
ConditionalBlock/While ops.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from . import create_parameter

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "batch_norm", "layer_norm",
    "group_norm", "instance_norm", "prelu", "cond", "case", "switch_case",
    "while_loop",
]


def _F():
    import paddle_tpu.nn.functional as F

    return F


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully-connected builder (reference static/nn/common.py fc)."""
    shape = [int(s) for s in x.shape]
    in_dim = int(np.prod(shape[num_flatten_dims:]))
    w = create_parameter([in_dim, size], name=None if name is None else f"{name}.w")
    F = _F()
    # -1 keeps the batch dims dynamic (the build-time placeholder shape has
    # None dims concretized to 1 — never bake those in)
    flat = x if len(shape) == num_flatten_dims + 1 and shape[-1] == in_dim \
        else x.reshape([-1, in_dim])
    from ..ops.linalg import matmul

    out = matmul(flat, w)
    if bias_attr is not False:
        b = create_parameter([size], is_bias=True,
                             name=None if name is None else f"{name}.b")
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    """Embedding lookup builder (reference static/nn/common.py embedding)."""
    w = create_parameter(list(size), dtype=dtype,
                         name=None if name is None else f"{name}.w")
    F = _F()
    return F.embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else (filter_size,) * 2)
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    w = create_parameter([num_filters, in_ch // groups, *k],
                         name=None if name is None else f"{name}.w")
    b = (None if bias_attr is False else
         create_parameter([num_filters], is_bias=True,
                          name=None if name is None else f"{name}.b"))
    F = _F()
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else (filter_size,) * 3)
    in_ch = int(input.shape[1 if data_format == "NCDHW" else -1])
    w = create_parameter([num_filters, in_ch // groups, *k],
                         name=None if name is None else f"{name}.w")
    b = (None if bias_attr is False else
         create_parameter([num_filters], is_bias=True,
                          name=None if name is None else f"{name}.b"))
    F = _F()
    out = F.conv3d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    """Static batch_norm: batch statistics in the training graph (the
    reference's training-mode path; serving graphs use the exported
    inference program where statistics are frozen)."""
    from ..core.tensor import to_tensor

    C = int(input.shape[1 if data_layout == "NCHW" else -1])
    one = np.ones(C, np.float32)
    scale = create_parameter([C], default_initializer=lambda s: one,
                             name=None if name is None else f"{name}.scale")
    bias = create_parameter([C], is_bias=True,
                            name=None if name is None else f"{name}.bias")
    if is_test:
        raise NotImplementedError(
            "static.nn.batch_norm(is_test=True) has no learned running "
            "statistics in this builder — export the trained program with "
            "save_inference_model and run THAT for eval/serving")
    F = _F()
    # training graph: batch statistics
    rm = to_tensor(np.zeros(C, np.float32))
    rv = to_tensor(np.ones(C, np.float32))
    out = F.batch_norm(input, rm, rv, weight=scale, bias=bias,
                       training=True, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    w = create_parameter(shape, default_initializer=lambda s: np.ones(s, np.float32)) if scale else None
    b = create_parameter(shape, is_bias=True) if shift else None
    F = _F()
    out = F.layer_norm(input, normalized_shape=shape, weight=w, bias=b,
                       epsilon=epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    C = int(input.shape[1 if data_layout == "NCHW" else -1])
    w = create_parameter([C], default_initializer=lambda s: np.ones(s, np.float32))
    b = create_parameter([C], is_bias=True)
    F = _F()
    out = F.group_norm(input, num_groups=groups, weight=w, bias=b,
                       epsilon=epsilon, data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    C = int(input.shape[1])
    w = create_parameter([C], default_initializer=lambda s: np.ones(s, np.float32))
    b = create_parameter([C], is_bias=True)
    F = _F()
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [int(x.shape[1 if data_format == "NCHW" else -1])]
    else:
        raise NotImplementedError(
            "prelu mode='element' needs a per-element weight; the functional "
            "prelu supports scalar/per-channel weights (as the common cases)")
    a = create_parameter(
        shape, default_initializer=lambda s: np.full(s, 0.25, np.float32))
    F = _F()
    return F.prelu(x, a, data_format=data_format)


# -- control flow (reference static/nn/control_flow.py) ----------------------
#
# Build-time predicates are concrete (placeholders hold zeros), so the cond
# must be RECORDED, not taken: each op returns tensors carrying a replay
# closure that re-invokes the user's branch builders at compile time, when
# placeholders hold traced values — the dy2static runtime then lowers to
# lax.cond / lax.while_loop. Restriction (as in the reference): don't
# create parameters inside a branch/body; build them outside.


import contextlib


@contextlib.contextmanager
def _swap_captured(fns, cache):
    """Branch/body closures may capture INTERMEDIATE tensors (h = x * 2)
    whose ._value is the stale build-time constant at replay time —
    resolve every captured Tensor through the replay cache and swap the
    live value in for the duration of the re-invocation."""
    from ..core.dispatch import recompute_value

    seen: dict[int, Tensor] = {}

    def collect(fn, depth=0):
        if depth > 4 or not callable(fn):
            return
        for cell in (getattr(fn, "__closure__", None) or ()):
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if isinstance(v, Tensor):
                seen.setdefault(id(v), v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, Tensor):
                        seen.setdefault(id(x), x)
            elif callable(v):
                collect(v, depth + 1)

    for f in fns:
        collect(f)
    old = {i: t._value for i, t in seen.items()}
    for i, t in seen.items():
        t._value = recompute_value(t, cache)
    try:
        yield
    finally:
        for i, t in seen.items():
            t._value = old[i]


def _record_control_flow(build_outputs, replay_fn):
    """Wrap build-time outputs with a replay closure (the pattern
    static.gradients uses)."""
    from ..jit.dy2static.runtime import _flatten

    leaves, treedef = _flatten(build_outputs)
    outs: list = []

    def replay(cache):
        if outs and id(outs[0]) in cache:
            return [cache[id(o)] for o in outs]
        vals = replay_fn(cache)
        for o, v in zip(outs, vals):
            cache[id(o)] = v
        return vals

    wrapped = []
    for i, leaf in enumerate(leaves):
        v = leaf._value if isinstance(leaf, Tensor) else leaf
        t = Tensor._wrap(v)
        t._recompute = (replay, i)
        outs.append(t)
        wrapped.append(t)
    import jax.tree_util as jtu

    return jtu.tree_unflatten(treedef, wrapped)


def cond(pred, true_fn=None, false_fn=None, name=None):
    """paddle.static.nn.cond: both branches trace under lax.cond in the
    compiled program; concrete predicates keep python semantics
    (ConditionalBlockOp role)."""
    from ..core.autograd import no_grad, pure_mode
    from ..core.dispatch import recompute_value
    from ..jit.dy2static import runtime as _jst

    t_fn = true_fn or (lambda: None)
    f_fn = false_fn or (lambda: None)
    from ..core.autograd import in_pure_mode

    if in_pure_mode():
        # invoked from inside another control-flow replay (e.g. nested
        # case): an intermediate pred tensor's ._value is the stale
        # build-time constant — re-replay it against the CURRENT
        # (traced) placeholder values and convert directly
        p = (recompute_value(pred, {}) if isinstance(pred, Tensor) else pred)
        return _jst.convert_ifelse(Tensor._wrap(p), t_fn, f_fn)
    # build-time value: concrete pred picks one branch eagerly
    build_out = _jst.convert_ifelse(pred, t_fn, f_fn)
    pred_t = pred

    def replay_fn(cache):
        p = recompute_value(pred_t, cache) if isinstance(pred_t, Tensor) else pred_t
        with pure_mode(), no_grad(), _swap_captured((t_fn, f_fn), cache):
            out = _jst.convert_ifelse(Tensor._wrap(p), t_fn, f_fn)
        leaves, _ = _jst._flatten(out)
        return [l._value if isinstance(l, Tensor) else l for l in leaves]

    return _record_control_flow(build_out, replay_fn)


def case(pred_fn_pairs, default=None, name=None):
    """First predicate that holds wins (reference control_flow.py case)."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    (pred, fn), rest = pred_fn_pairs[0], pred_fn_pairs[1:]
    if not rest:
        return cond(pred, fn, default if default is not None else fn)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer dispatch (reference control_flow.py switch_case)."""
    pairs = sorted(branch_fns.items() if isinstance(branch_fns, dict)
                   else list(enumerate(branch_fns)))
    pred_fn = [(branch_index == int(i), fn) for i, fn in pairs]
    return case(pred_fn, default=default)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop -> lax.while_loop in the compiled program
    (WhileOp role). loop_vars is a list; returns the final list."""
    from ..core.autograd import no_grad, pure_mode
    from ..core.dispatch import recompute_value
    from ..jit.dy2static import runtime as _jst

    body_t = lambda *vs: tuple(body_fn(*vs))
    from ..core.autograd import in_pure_mode

    if in_pure_mode():  # nested inside another control-flow replay
        vals = [recompute_value(v, {}) if isinstance(v, Tensor) else v
                for v in loop_vars]
        return list(_jst.convert_while(
            cond_fn, body_t, tuple(Tensor._wrap(v) for v in vals)))
    build_out = list(_jst.convert_while(cond_fn, body_t, tuple(loop_vars)))
    init_vars = list(loop_vars)

    def replay_fn(cache):
        vals = [recompute_value(v, cache) if isinstance(v, Tensor) else v
                for v in init_vars]
        with pure_mode(), no_grad(), _swap_captured((cond_fn, body_fn), cache):
            out = _jst.convert_while(
                cond_fn, body_t, tuple(Tensor._wrap(v) for v in vals))
        return [o._value if isinstance(o, Tensor) else o for o in out]

    return list(_record_control_flow(tuple(build_out), replay_fn))
