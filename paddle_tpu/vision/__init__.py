from . import datasets, models, ops, transforms  # noqa: F401

__all__ = ["datasets", "models", "ops", "transforms"]
