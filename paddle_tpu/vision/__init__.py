from . import datasets, models, transforms  # noqa: F401

__all__ = ["datasets", "models", "transforms"]
