"""Vision transforms (reference: /root/reference/python/paddle/vision/transforms/).
Operate on numpy CHW float arrays (host-side preprocessing)."""
from __future__ import annotations

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3
        tgt = (arr.shape[0],) + self.size if chw else self.size
        return np.asarray(jax.image.resize(arr, tgt, method="bilinear"))


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        return arr[..., i : i + th, j : j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            pad = [(0, 0)] * (arr.ndim - 2) + [(self.padding, self.padding)] * 2
            arr = np.pad(arr, pad)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i : i + th, j : j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)
