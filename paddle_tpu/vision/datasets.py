"""Vision datasets (reference: /root/reference/python/paddle/vision/datasets/).

No-network environment: MNIST reads the standard idx files from ``root`` when
present, otherwise generates a deterministic synthetic-but-learnable digit set
(class-template + noise) so the LeNet end-to-end config (BASELINE config #1)
runs hermetically — the same role the reference's fake-device CI plays.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers"]


def _synthetic_images(n, num_classes, hw, seed, channels=1, template_seed=1234):
    # templates are shared across train/test splits (template_seed); only the
    # sample noise differs per split, so the task generalizes
    h, w = hw
    templates = np.random.RandomState(template_seed).rand(
        num_classes, channels, h, w).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    noise = rng.rand(n, channels, h, w).astype(np.float32) * 0.8
    images = templates[labels] + noise
    images = (images / images.max() * 255).astype(np.uint8)
    return images, labels


class MNIST(Dataset):
    """MNIST; synthetic fallback when idx files are absent."""

    NUM_CLASSES = 10
    HW = (28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None, root=None):
        self.mode = mode
        self.transform = transform
        images = labels = None
        root = root or image_path
        if root and os.path.isdir(root):
            prefix = "train" if mode == "train" else "t10k"
            img_f = os.path.join(root, f"{prefix}-images-idx3-ubyte.gz")
            lbl_f = os.path.join(root, f"{prefix}-labels-idx1-ubyte.gz")
            if os.path.exists(img_f) and os.path.exists(lbl_f):
                images = self._read_idx_images(img_f)
                labels = self._read_idx_labels(lbl_f)
        if images is None:
            n = 2048 if mode == "train" else 512
            images, labels = _synthetic_images(
                n, self.NUM_CLASSES, self.HW, seed=0 if mode == "train" else 1)
            images = images[:, 0]  # HW, single channel
        self.images = images
        self.labels = labels

    @staticmethod
    def _read_idx_images(path):
        with gzip.open(path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _read_idx_labels(path):
        with gzip.open(path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None]  # 1,28,28
        img = img / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def get_arrays(self):
        """Whole-dataset contiguous arrays for the native batcher (same
        values __getitem__ yields); None when a transform must run per item.
        Computed per call (once per epoch) — a cached f32 copy would pin 4x
        the dataset's memory for its whole lifetime."""
        if self.transform is not None:
            return None
        return (self.images.astype(np.float32)[:, None] / 127.5 - 1.0,
                np.asarray(self.labels, np.int64))

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        n = 2048 if mode == "train" else 512
        self.images, self.labels = _synthetic_images(
            n, self.NUM_CLASSES, (32, 32), seed=2 if mode == "train" else 3, channels=3)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Dataset):
    """102-category Oxford flowers (reference
    python/paddle/vision/datasets/flowers.py: items are (HWC uint8 image ->
    transform, int64 label in [0,102))). Synthetic class-templated images,
    deterministic per split (train/valid/test)."""

    NUM_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        n = {"train": 2040, "valid": 510, "test": 1020}.get(mode, 1020)
        seed = {"train": 8, "valid": 9, "test": 10}.get(mode, 10)
        self.images, self.labels = _synthetic_images(
            n, self.NUM_CLASSES, (32, 32), seed=seed, channels=3)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)
