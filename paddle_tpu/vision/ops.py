"""paddle.vision.ops parity surface (reference
/root/reference/python/paddle/vision/ops.py): detection functionals
re-exported from the op registry + the shared ConvNormActivation block
(reference :1796) used across the model zoo."""
from __future__ import annotations

from .. import nn
from ..ops.registry import OPS

__all__ = [
    "ConvNormActivation", "DeformConv2D", "deform_conv2d", "nms",
    "roi_align", "roi_pool", "yolo_box", "yolo_loss", "prior_box",
    "box_coder", "matrix_nms", "distribute_fpn_proposals",
    "generate_proposals",
]


class ConvNormActivation(nn.Sequential):
    """Conv2D -> norm -> activation (reference vision/ops.py:1796). The one
    block the whole zoo composes: norm_layer/activation_layer None skips
    that stage; bias defaults to norm_layer is None."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=nn.BatchNorm2D,
                 activation_layer=nn.ReLU, dilation=1, bias=None):
        if padding is None:
            k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
            padding = (k - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(in_channels, out_channels, kernel_size,
                            stride=stride, padding=padding, dilation=dilation,
                            groups=groups,
                            bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


def _export(name):
    # resolved lazily: the op table finishes registering (ops.parity import)
    # after the vision package is first imported
    def wrapper(*args, **kwargs):
        return OPS[name].fn(*args, **kwargs)

    wrapper.__name__ = name
    return wrapper


deform_conv2d = _export("deform_conv2d")
nms = _export("nms")
roi_align = _export("roi_align")
roi_pool = _export("roi_pool")
yolo_box = _export("yolo_box")
yolo_loss = _export("yolo_loss")
prior_box = _export("prior_box")
box_coder = _export("box_coder")
matrix_nms = _export("matrix_nms")
distribute_fpn_proposals = _export("distribute_fpn_proposals")
generate_proposals = _export("generate_proposals")


class DeformConv2D(nn.Layer):
    """Deformable conv layer (reference paddle.vision.ops.DeformConv2D):
    forward takes (x, offset, mask=None); weight/bias are parameters."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I

        k = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
             else tuple(kernel_size))
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]],
            default_initializer=I.XavierUniform())
        self.bias = (None if bias_attr is False
                     else self.create_parameter([out_channels], is_bias=True))

    def forward(self, x, offset, mask=None):
        args = [x, offset, self.weight]
        kwargs = dict(self._cfg, mask=mask)
        if self.bias is not None:
            kwargs["bias"] = self.bias
        return OPS["deform_conv2d"].fn(*args, **kwargs)
