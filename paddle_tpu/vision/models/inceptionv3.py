"""Inception v3 (reference python/paddle/vision/models/inceptionv3.py:488;
Szegedy 2015 factorized 7x7 / label-smoothing era architecture)."""
from __future__ import annotations

from ... import nn
from ..ops import ConvNormActivation

__all__ = ["InceptionV3", "inception_v3"]


class ConvBN(ConvNormActivation):
    def __init__(self, c_in, c_out, kernel, stride=1, padding=0):
        super().__init__(c_in, c_out, kernel, stride=stride, padding=padding)


def _cat(xs):
    from ... import ops as P

    return P.concat(xs, axis=1)


class InceptionA(nn.Layer):
    def __init__(self, c_in, pool_features):
        super().__init__()
        self.b1 = ConvBN(c_in, 64, 1)
        self.b2 = nn.Sequential(ConvBN(c_in, 48, 1),
                                ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(ConvBN(c_in, 64, 1),
                                ConvBN(64, 96, 3, padding=1),
                                ConvBN(96, 96, 3, padding=1))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                ConvBN(c_in, pool_features, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)])


class InceptionB(nn.Layer):
    """Grid reduction 35x35 -> 17x17."""

    def __init__(self, c_in):
        super().__init__()
        self.b1 = ConvBN(c_in, 384, 3, stride=2)
        self.b2 = nn.Sequential(ConvBN(c_in, 64, 1),
                                ConvBN(64, 96, 3, padding=1),
                                ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b1(x), self.b2(x), self.pool(x)])


class InceptionC(nn.Layer):
    """Factorized 7x7 branches."""

    def __init__(self, c_in, c7):
        super().__init__()
        self.b1 = ConvBN(c_in, 192, 1)
        self.b2 = nn.Sequential(
            ConvBN(c_in, c7, 1),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b3 = nn.Sequential(
            ConvBN(c_in, c7, 1),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                ConvBN(c_in, 192, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)])


class InceptionD(nn.Layer):
    """Grid reduction 17x17 -> 8x8."""

    def __init__(self, c_in):
        super().__init__()
        self.b1 = nn.Sequential(ConvBN(c_in, 192, 1),
                                ConvBN(192, 320, 3, stride=2))
        self.b2 = nn.Sequential(
            ConvBN(c_in, 192, 1),
            ConvBN(192, 192, (1, 7), padding=(0, 3)),
            ConvBN(192, 192, (7, 1), padding=(3, 0)),
            ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b1(x), self.b2(x), self.pool(x)])


class InceptionE(nn.Layer):
    """Expanded-filter-bank output blocks."""

    def __init__(self, c_in):
        super().__init__()
        self.b1 = ConvBN(c_in, 320, 1)
        self.b2_stem = ConvBN(c_in, 384, 1)
        self.b2_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b2_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3_stem = nn.Sequential(ConvBN(c_in, 448, 1),
                                     ConvBN(448, 384, 3, padding=1))
        self.b3_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b4 = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                ConvBN(c_in, 192, 1))

    def forward(self, x):
        h2 = self.b2_stem(x)
        h3 = self.b3_stem(x)
        return _cat([self.b1(x),
                     _cat([self.b2_a(h2), self.b2_b(h2)]),
                     _cat([self.b3_a(h3), self.b3_b(h3)]),
                     self.b4(x)])


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            ConvBN(3, 32, 3, stride=2),
            ConvBN(32, 32, 3),
            ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            ConvBN(64, 80, 1),
            ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        from ... import ops as P

        h = self.blocks(self.stem(x))
        if self.with_pool:
            h = self.pool(h)
        if self.num_classes > 0:
            h = self.fc(self.drop(P.flatten(h, start_axis=1)))
        return h


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
