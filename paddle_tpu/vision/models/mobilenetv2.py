"""MobileNetV2 (reference python/paddle/vision/models/mobilenetv2.py;
Sandler 2018 inverted residuals + linear bottlenecks)."""
from __future__ import annotations

from ... import nn
from ..ops import ConvNormActivation

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(ConvNormActivation):
    def __init__(self, c_in, c_out, kernel=3, stride=1, groups=1):
        super().__init__(c_in, c_out, kernel, stride=stride, groups=groups,
                         activation_layer=nn.ReLU6)


class InvertedResidual(nn.Layer):
    def __init__(self, c_in, c_out, stride, expand_ratio):
        super().__init__()
        hidden = int(round(c_in * expand_ratio))
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(c_in, hidden, kernel=1))
        layers += [
            ConvBNReLU(hidden, hidden, stride=stride, groups=hidden),  # dw
            nn.Conv2D(hidden, c_out, 1, bias_attr=False),  # linear pw
            nn.BatchNorm2D(c_out),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        c_in = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        feats = [ConvBNReLU(3, c_in, stride=2)]
        for t, c, n, s in cfg:
            c_out = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(
                    c_in, c_out, s if i == 0 else 1, t))
                c_in = c_out
        feats.append(ConvBNReLU(c_in, last, kernel=1))
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last, num_classes))

    def forward(self, x):
        from ... import ops as P

        h = self.features(x)
        if self.with_pool:
            h = self.pool(h)
        if self.num_classes > 0:
            h = self.classifier(P.flatten(h, start_axis=1))
        return h


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
