"""DenseNet (reference python/paddle/vision/models/densenet.py; Huang 2017
dense connectivity: each layer consumes every earlier feature map)."""
from __future__ import annotations

from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]

_CFGS = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, c_in, growth, bn_size=4, dropout=0.0):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(c_in)
        self.conv1 = nn.Conv2D(c_in, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout > 0 else None

    def forward(self, x):
        from ... import ops as P

        h = self.conv1(self.relu(self.norm1(x)))
        h = self.conv2(self.relu(self.norm2(h)))
        if self.dropout is not None:
            h = self.dropout(h)
        return P.concat([x, h], axis=1)


class _Transition(nn.Layer):
    def __init__(self, c_in, c_out):
        super().__init__()
        self.norm = nn.BatchNorm2D(c_in)
        self.conv = nn.Conv2D(c_in, c_out, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        init_c, growth, blocks = _CFGS[layers]
        feats = [
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        ]
        c = init_c
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if bi != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        from ... import ops as P

        h = self.features(x)
        if self.with_pool:
            h = self.pool(h)
        if self.num_classes > 0:
            h = self.classifier(P.flatten(h, start_axis=1))
        return h


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)
