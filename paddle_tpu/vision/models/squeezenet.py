"""SqueezeNet (reference python/paddle/vision/models/squeezenet.py;
Iandola 2016 fire modules: squeeze 1x1 then expand 1x1+3x3)."""
from __future__ import annotations

from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(nn.Layer):
    def __init__(self, c_in, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(c_in, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        from ... import ops as P

        s = self.relu(self.squeeze(x))
        return P.concat([self.relu(self.expand1(s)),
                         self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version == "1.0":
            feats = [
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256),
            ]
        else:  # 1.1
            feats = [
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            ]
        self.features = nn.Sequential(*feats)
        self.num_classes = num_classes
        self.with_pool = with_pool
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        from ... import ops as P

        h = self.features(x)
        if self.num_classes > 0:
            h = self.classifier(h)
        if self.with_pool:
            h = self.pool(h)
        return P.flatten(h, start_axis=1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)
