"""ShuffleNetV2 (reference python/paddle/vision/models/shufflenetv2.py:195;
Ma 2018 — channel split + shuffle units)."""
from __future__ import annotations

from ... import nn
from ..ops import ConvNormActivation

__all__ = [
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}
_REPEATS = [4, 8, 4]


class ConvBNAct(ConvNormActivation):
    def __init__(self, c_in, c_out, kernel, stride=1, groups=1, act="relu"):
        super().__init__(
            c_in, c_out, kernel, stride=stride, groups=groups,
            activation_layer={"relu": nn.ReLU, "swish": nn.Swish,
                              None: None}[act])


def _shuffle(x, groups=2):
    from ...nn import functional as F

    return F.channel_shuffle(x, groups)


class ShuffleUnit(nn.Layer):
    """Stride-1 unit: split channels, transform the right half, shuffle."""

    def __init__(self, channels, act):
        super().__init__()
        assert channels % 2 == 0
        c = channels // 2
        self.branch = nn.Sequential(
            ConvBNAct(c, c, 1, act=act),
            ConvBNAct(c, c, 3, groups=c, act=None),  # dw
            ConvBNAct(c, c, 1, act=act),
        )

    def forward(self, x):
        from ... import ops as P

        left, right = P.split(x, 2, axis=1)
        out = P.concat([left, self.branch(right)], axis=1)
        return _shuffle(out)


class ShuffleUnitDS(nn.Layer):
    """Downsample unit: both branches stride 2, channels double."""

    def __init__(self, c_in, c_out, act):
        super().__init__()
        c = c_out // 2
        self.left = nn.Sequential(
            ConvBNAct(c_in, c_in, 3, stride=2, groups=c_in, act=None),
            ConvBNAct(c_in, c, 1, act=act),
        )
        self.right = nn.Sequential(
            ConvBNAct(c_in, c, 1, act=act),
            ConvBNAct(c, c, 3, stride=2, groups=c, act=None),
            ConvBNAct(c, c, 1, act=act),
        )

    def forward(self, x):
        from ... import ops as P

        out = P.concat([self.left(x), self.right(x)], axis=1)
        return _shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        chans = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(ConvBNAct(3, chans[0], 3, stride=2, act=act),
                                  nn.MaxPool2D(3, stride=2, padding=1))
        stages = []
        c_in = chans[0]
        for stage_i, reps in enumerate(_REPEATS):
            c_out = chans[stage_i + 1]
            stages.append(ShuffleUnitDS(c_in, c_out, act))
            stages += [ShuffleUnit(c_out, act) for _ in range(reps - 1)]
            c_in = c_out
        self.stages = nn.Sequential(*stages)
        self.head = ConvBNAct(c_in, chans[-1], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)

    def forward(self, x):
        from ... import ops as P

        h = self.head(self.stages(self.stem(x)))
        if self.with_pool:
            h = self.pool(h)
        if self.num_classes > 0:
            h = self.fc(P.flatten(h, start_axis=1))
        return h


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
