"""MobileNetV1 (reference python/paddle/vision/models/mobilenetv1.py;
Howard 2017 depthwise-separable convolutions)."""
from __future__ import annotations

from ... import nn
from ..ops import ConvNormActivation

__all__ = ["MobileNetV1", "mobilenet_v1"]


class ConvBNReLU(ConvNormActivation):
    def __init__(self, c_in, c_out, kernel=3, stride=1, groups=1):
        super().__init__(c_in, c_out, kernel, stride=stride, groups=groups)


class DepthwiseSeparable(nn.Layer):
    def __init__(self, c_in, c_out, stride):
        super().__init__()
        self.dw = ConvBNReLU(c_in, c_in, 3, stride=stride, groups=c_in)
        self.pw = ConvBNReLU(c_in, c_out, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [  # (c_in, c_out, stride)
            (32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
            (256, 256, 1), (256, 512, 2),
            (512, 512, 1), (512, 512, 1), (512, 512, 1), (512, 512, 1),
            (512, 512, 1), (512, 1024, 2), (1024, 1024, 1),
        ]
        feats = [ConvBNReLU(3, c(32), stride=2)]
        feats += [DepthwiseSeparable(c(a), c(b), s) for a, b, s in cfg]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        from ... import ops as P

        h = self.features(x)
        if self.with_pool:
            h = self.pool(h)
        if self.num_classes > 0:
            h = self.fc(P.flatten(h, start_axis=1))
        return h


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
