"""MobileNetV3 Small/Large (reference
python/paddle/vision/models/mobilenetv3.py:184; Howard 2019 — squeeze-
excitation bottlenecks with hardswish activations)."""
from __future__ import annotations

from ... import nn
from ..ops import ConvNormActivation
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class ConvBNAct(ConvNormActivation):
    def __init__(self, c_in, c_out, kernel=3, stride=1, groups=1, act="HS"):
        super().__init__(
            c_in, c_out, kernel, stride=stride, groups=groups,
            activation_layer={"HS": nn.Hardswish, "RE": nn.ReLU,
                              None: None}[act])


class SqueezeExcitation(nn.Layer):
    def __init__(self, channels, squeeze_ratio=4):
        super().__init__()
        squeeze = _make_divisible(channels // squeeze_ratio)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, squeeze, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze, channels, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class Bneck(nn.Layer):
    """Inverted residual with optional SE, per (k, exp, out, se, act, s)."""

    def __init__(self, c_in, kernel, exp, c_out, use_se, act, stride):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if exp != c_in:
            layers.append(ConvBNAct(c_in, exp, 1, act=act))
        layers.append(ConvBNAct(exp, exp, kernel, stride=stride, groups=exp,
                                act=act))
        if use_se:
            layers.append(SqueezeExcitation(exp))
        layers.append(ConvBNAct(exp, c_out, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, hidden, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        c_in = c(16)
        feats = [ConvBNAct(3, c_in, 3, stride=2, act="HS")]
        for k, exp, out, se, act, s in cfg:
            feats.append(Bneck(c_in, k, c(exp), c(out), se, act, s))
            c_in = c(out)
        last = c(last_exp)
        feats.append(ConvBNAct(c_in, last, 1, act="HS"))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last, hidden), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(hidden, num_classes))

    def forward(self, x):
        from ... import ops as P

        h = self.features(x)
        if self.with_pool:
            h = self.pool(h)
        if self.num_classes > 0:
            h = self.classifier(P.flatten(h, start_axis=1))
        return h


_SMALL = [  # kernel, expansion, out, SE, activation, stride
    (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
    (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
    (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
    (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
    (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
    (5, 576, 96, True, "HS", 1),
]
_LARGE = [
    (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
    (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
    (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
    (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
    (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
    (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
    (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
    (5, 960, 160, True, "HS", 1),
]


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
