from .alexnet import AlexNet, alexnet  # noqa: F401
from .densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201,
)
from .lenet import LeNet  # noqa: F401
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401

__all__ = [
    "LeNet", "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
    "resnet152", "AlexNet", "alexnet", "VGG", "vgg11", "vgg13", "vgg16",
    "vgg19", "MobileNetV2", "mobilenet_v2",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
]
