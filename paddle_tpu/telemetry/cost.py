"""Roofline cost model: FLOPs + HBM bytes per compiled trace.

The ROADMAP's north-star is "as fast as the hardware allows" — a claim that
is only checkable against a cost model. Training has had an MFU number
since round 1 (``profiler.mfu``); serving has never been attributed
flop-by-flop. This module closes that gap with the same per-op cost
discipline GSPMD uses to reason about partitioned programs:

- :func:`jaxpr_cost` walks a (closed) jaxpr and accumulates **FLOPs**
  (``dot_general`` exactly from its dimension numbers — every matmul and
  einsum in the model lowers to it — plus elementwise/reduction ops at one
  flop per output/input element) and **HBM bytes** (the trace's top-level
  inputs + outputs: the minimum traffic a perfectly-fused execution must
  move, which for a memory-bound decode step — weights + KV pool — is the
  roofline-relevant number).
- :func:`estimate_fn_cost` is the entry point the serving engine calls at
  trace time: ``jax.make_jaxpr`` on the exact python callable + arguments
  the engine is about to jit, so the estimate covers precisely the padded
  shapes the compiled trace will execute (bucket padding included).
- :func:`xla_cost_analysis` cross-checks against the backend's own
  ``compiled.cost_analysis()`` where the jax version/backend exposes it
  (it re-traces and compiles, so it is a tool for tests and offline
  analysis, never the serving hot path).
- :func:`register_trace` records the estimate per ``(callable, bucket)``
  in a process-global registry (fingerprinted by model config so identical
  engines share one estimate) and publishes ``trace_flops`` /
  ``trace_bytes`` / ``trace_arithmetic_intensity`` gauges.
- :func:`platform_peaks` + :func:`roofline_time_s` turn an estimate into
  the roofline-model lower bound on step wall time,
  ``max(flops / peak_flops, bytes / peak_bw)``; the engine divides it by
  the measured step time into an achieved-fraction-of-roofline gauge
  (``serving_roofline_frac``) — the serving analogue of MFU.

Peaks default per platform (same public-spec numbers as ``profiler``'s MFU
accounting; CPU values are placeholders for shape, not truth) and are
overridable with ``$PADDLE_TPU_PEAK_FLOPS`` / ``$PADDLE_TPU_PEAK_BW``.
"""
from __future__ import annotations

import os
import threading

import numpy as np

from .metrics import registry
from ..analysis import locksan

__all__ = [
    "jaxpr_cost", "estimate_fn_cost", "xla_cost_analysis",
    "register_trace", "lookup", "traces", "clear",
    "platform_peaks", "roofline_time_s", "achieved_fraction",
]

# primitives that move/reshape data but compute nothing (counted as zero
# flops; their traffic is covered by the whole-trace byte accounting)
_ZERO_FLOP = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "convert_element_type",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate",
    "gather", "scatter", "squeeze", "expand_dims", "iota", "rev",
    "pad", "copy", "stop_gradient", "split", "bitcast_convert_type",
    "device_put", "constant", "empty", "select_and_scatter_add",
})

# reductions: one flop per *input* element (the sum/max tree)
_REDUCE_PREFIXES = ("reduce_", "cum", "arg")


def _aval_elems(aval) -> int:
    try:
        n = 1
        for s in aval.shape:
            n *= int(s)
        return n
    except Exception:  # lint: allow-silent(cost model is advisory; unknown aval counts as 0)
        return 0


def _aval_bytes(aval) -> int:
    try:
        return _aval_elems(aval) * np.dtype(aval.dtype).itemsize
    except Exception:  # lint: allow-silent(cost model is advisory; unknown dtype counts as 0)
        return 0


def _dot_general_flops(eqn) -> int:
    """2*M*N*K*batch from the dimension numbers — exact for every matmul
    and einsum (they all lower to dot_general)."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = 1
    for i in lb:
        batch *= int(lhs[i])
    k = 1
    for i in lc:
        k *= int(lhs[i])
    m = 1
    for i in range(len(lhs)):
        if i not in lc and i not in lb:
            m *= int(lhs[i])
    n = 1
    for i in range(len(rhs)):
        if i not in rc and i not in rb:
            n *= int(rhs[i])
    return 2 * batch * m * n * k


def _sub_jaxprs(value):
    """Yield any Jaxpr/ClosedJaxpr objects hiding in an eqn param (pjit,
    custom_jvp/vjp, remat, scan bodies, ...) — generic recursion so the
    walk survives jax version drift in primitive names."""
    vals = value if isinstance(value, (tuple, list)) else (value,)
    for v in vals:
        jx = getattr(v, "jaxpr", None)
        if jx is not None and hasattr(jx, "eqns"):
            yield jx                     # ClosedJaxpr
        elif hasattr(v, "eqns"):
            yield v                      # raw Jaxpr


def _walk(jaxpr, acc):
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "dot_general":
            f = _dot_general_flops(eqn)
            acc["matmul_flops"] += f
            continue
        inner = False
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                inner = True
                _walk(sub, acc)
        if inner:
            continue
        if prim in _ZERO_FLOP:
            continue
        if prim.startswith(_REDUCE_PREFIXES):
            acc["elementwise_flops"] += sum(
                _aval_elems(v.aval) for v in eqn.invars
                if hasattr(v, "aval"))
            continue
        # default: elementwise — one flop per output element
        acc["elementwise_flops"] += sum(
            _aval_elems(v.aval) for v in eqn.outvars)


def jaxpr_cost(closed_jaxpr) -> dict:
    """FLOPs + HBM bytes of one trace. ``bytes`` counts the top-level
    inputs (weights, KV pool, tokens) plus outputs — the minimum HBM
    traffic of the compiled program, which is the roofline bound for a
    memory-bound step. Arithmetic intensity is flops/byte."""
    jx = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    acc = {"matmul_flops": 0, "elementwise_flops": 0}
    _walk(jx, acc)
    in_bytes = sum(_aval_bytes(v.aval) for v in jx.invars)
    in_bytes += sum(_aval_bytes(v.aval) for v in jx.constvars)
    out_bytes = sum(_aval_bytes(v.aval) for v in jx.outvars)
    flops = acc["matmul_flops"] + acc["elementwise_flops"]
    nbytes = in_bytes + out_bytes
    return {
        "flops": flops,
        "matmul_flops": acc["matmul_flops"],
        "elementwise_flops": acc["elementwise_flops"],
        "bytes": nbytes,
        "input_bytes": in_bytes,
        "output_bytes": out_bytes,
        "arithmetic_intensity": flops / nbytes if nbytes else 0.0,
    }


def estimate_fn_cost(fn, *args, **kwargs) -> dict:
    """Trace ``fn`` abstractly (``jax.make_jaxpr`` — no XLA compile) and
    walk the jaxpr. The caller is responsible for suspending any python
    side effects the traced function carries (the engine's trace
    counters). ``fn`` is traced through a fresh wrapper object so jax's
    tracing cache never aliases this probe with the caller's own
    ``jax.jit(fn)`` — the jit must still see (and python-execute) its own
    first trace."""
    import jax

    def _probe(*a, **k):
        return fn(*a, **k)

    return jaxpr_cost(jax.make_jaxpr(_probe)(*args, **kwargs))


def xla_cost_analysis(fn, *args, **kwargs) -> dict | None:
    """Best-effort ``compiled.cost_analysis()`` cross-check: returns the
    backend's own {flops, bytes accessed, ...} dict, or None when the jax
    version/backend does not expose it. Re-traces AND compiles — offline
    use only."""
    try:
        import jax

        lowered = jax.jit(fn).lower(*args, **kwargs)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):      # older jax: one per device
            ca = ca[0] if ca else None
        return dict(ca) if ca else None
    except Exception:  # lint: allow-silent(xla cost analysis is version-dependent; None = unavailable)
        return None


# ---------------------------------------------------------------------------
# trace-cost registry (per callable+bucket, fingerprinted)
# ---------------------------------------------------------------------------

_LOCK = locksan.Lock("cost.registry")
_TRACES: dict[tuple, dict] = {}     # (callable, bucket) -> entry
_CM = None


def _cost_metrics():
    global _CM
    if _CM is None:
        reg = registry()
        ls = ("callable", "bucket")
        _CM = (
            reg.gauge("trace_flops",
                      "modeled FLOPs of one compiled trace", ls),
            reg.gauge("trace_bytes",
                      "modeled HBM bytes (inputs+outputs) of one compiled "
                      "trace", ls),
            reg.gauge("trace_arithmetic_intensity",
                      "modeled flops/byte of one compiled trace", ls),
        )
    return _CM


def register_trace(name: str, bucket: str, cost: dict, *,
                   fingerprint=None, **meta) -> dict:
    """Record one trace's cost estimate (idempotent per (name, bucket));
    publishes the ``trace_*`` gauges. Returns the stored entry."""
    entry = {"callable": name, "bucket": str(bucket),
             "fingerprint": fingerprint, **cost, **meta}
    with _LOCK:
        _TRACES[(name, str(bucket))] = entry
    fl, by, ai = _cost_metrics()
    fl.labels(callable=name, bucket=str(bucket)).set(cost.get("flops", 0))
    by.labels(callable=name, bucket=str(bucket)).set(cost.get("bytes", 0))
    ai.labels(callable=name, bucket=str(bucket)).set(
        cost.get("arithmetic_intensity", 0.0))
    return entry


def lookup(name: str, bucket: str, fingerprint=None) -> dict | None:
    """A previously-registered estimate — only when the fingerprint (model
    config + engine geometry) matches, so two different models sharing a
    bucket label never share a cost."""
    with _LOCK:
        entry = _TRACES.get((name, str(bucket)))
    if entry is None:
        return None
    if fingerprint is not None and entry.get("fingerprint") != fingerprint:
        return None
    return dict(entry)


def traces() -> list[dict]:
    with _LOCK:
        return [dict(e) for e in _TRACES.values()]


def clear():
    with _LOCK:
        _TRACES.clear()


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

# peak dense flop/s (same public-spec table as profiler.peak_flops) and
# peak HBM bandwidth bytes/s per chip; CPU entries are placeholders that
# give the *shape* of the number on dev hosts, not truth
_PEAKS = {
    "tpu": (197e12, 819e9),     # v5e public spec: 197 bf16 TFLOP/s, 819 GB/s
    "axon": (197e12, 819e9),
    "cpu": (1e11, 2e10),
}


def platform_peaks(platform: str | None = None) -> dict:
    """{platform, flops_per_s, bytes_per_s}; ``$PADDLE_TPU_PEAK_FLOPS`` /
    ``$PADDLE_TPU_PEAK_BW`` override (bench hosts vary wildly)."""
    if platform is None:
        try:
            import jax

            platform = jax.devices()[0].platform
        except Exception:  # lint: allow-silent(no devices; cpu peaks are the fallback)
            platform = "cpu"
    flops, bw = _PEAKS.get(platform, _PEAKS["cpu"])
    try:
        flops = float(os.environ.get("PADDLE_TPU_PEAK_FLOPS") or flops)
        bw = float(os.environ.get("PADDLE_TPU_PEAK_BW") or bw)
    except ValueError:
        pass
    return {"platform": platform, "flops_per_s": flops, "bytes_per_s": bw}


def roofline_time_s(cost: dict, peaks: dict | None = None) -> float:
    """The roofline lower bound on wall time: compute-bound or
    memory-bound, whichever dominates."""
    peaks = peaks or platform_peaks()
    return max(cost.get("flops", 0) / peaks["flops_per_s"],
               cost.get("bytes", 0) / peaks["bytes_per_s"])


def achieved_fraction(cost: dict, wall_s: float,
                      peaks: dict | None = None) -> float | None:
    """roofline_time / measured wall — 1.0 means the step ran as fast as
    the roofline model says the hardware allows."""
    if not wall_s or wall_s <= 0:
        return None
    return roofline_time_s(cost, peaks) / float(wall_s)
