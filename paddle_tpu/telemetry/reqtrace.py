"""Request-scoped trace propagation: one Chrome trace per served request.

PR 10's fleet keeps spans per process: the gateway/router record into their
tracer, every replica (possibly a separate ``ProcReplica`` child) into its
own, and a request that crosses a replica pipe — or fails over mid-stream —
leaves no single timeline anyone can read. This module is the glue:

- **Trace context** — the gateway/router mint a ``trace_id`` per request
  (:func:`new_trace_id`) and propagate it through ``FleetRouter.submit``
  into the replica pipe protocol (a ``trace_id`` field on the ``add``
  command). Replica-side engine spans carry it as a span attr
  (``trace_id=...``, or ``trace_ids=[...]`` for batch-level decode ticks
  shared by several requests).
- **Wire format** — :func:`drain_request_spans` scans the process-global
  tracer for spans newer than a watermark that carry trace context and
  serializes them with **unix** timestamps (``tracing.mono_to_unix``), so
  hops from different processes land on one wall-clock timeline; replicas
  attach the drained spans to their periodic heartbeat events, which is
  what lets the first hop of a failover survive its replica's SIGKILL.
- **Merge** — :func:`merge_request_trace` generalizes PR 6's cross-rank
  Chrome merge from ranks to replicas: each hop (gateway/router process,
  every replica that served the request) becomes one process row, rebased
  through ``cluster.merge_traces``'s clock-corrected machinery (same-host
  replicas share a clock, but the ``offsets_s`` hook accepts per-source
  NTP-style estimates exactly like rank merges do).

``FleetRouter.request_trace(gid)`` assembles the sources and the gateway
serves the merged document at ``GET /v1/traces/<id>``;
``tools/trace_view.py`` renders it as a phase waterfall.
"""
from __future__ import annotations

import json
import os

from .tracing import mono_to_unix, tracer

__all__ = [
    "new_trace_id", "span_to_wire", "spans_to_wire", "drain_request_spans",
    "wire_trace_ids", "merge_request_trace",
]

TRACE_ATTR = "trace_id"
MULTI_ATTR = "trace_ids"


def new_trace_id(prefix: str = "req") -> str:
    return f"{prefix}-{os.urandom(6).hex()}"


def span_to_wire(span) -> dict:
    """One tracer Span as a process-independent dict: unix-stamped, attrs
    carried verbatim (the trace context rides in them)."""
    return {
        "name": span.name,
        "t0_unix": mono_to_unix(span.t0),
        "t1_unix": mono_to_unix(span.t1),
        "span_id": span.span_id,
        "parent_id": span.parent_id,
        "attrs": dict(span.attrs),
    }


def _carries_context(attrs: dict) -> bool:
    return bool(attrs.get(TRACE_ATTR) or attrs.get(MULTI_ATTR))


def spans_to_wire(spans) -> list[dict]:
    return [span_to_wire(s) for s in spans if _carries_context(s.attrs)]


def drain_request_spans(last_span_id: int, *,
                        engine_label=None) -> tuple[list[dict], int]:
    """New trace-context-carrying spans since ``last_span_id`` from the
    process-global tracer, serialized for the pipe. ``engine_label``
    filters to one engine's spans — two LocalReplica drivers share a
    process tracer, and each must heartbeat only its own engine's spans.
    Returns (wire spans, new watermark)."""
    out = []
    wm = int(last_span_id)
    for s in tracer().spans():
        if s.span_id <= last_span_id:
            continue
        wm = max(wm, s.span_id)
        a = s.attrs
        if not _carries_context(a):
            continue
        if engine_label is not None and \
                str(a.get("engine")) != str(engine_label):
            continue
        out.append(span_to_wire(s))
    return out, wm


def wire_trace_ids(wire_span: dict) -> tuple:
    """Every trace id a wire span belongs to (batch-level decode ticks
    carry several)."""
    a = wire_span.get("attrs") or {}
    tid = a.get(TRACE_ATTR)
    if tid:
        return (tid,)
    return tuple(a.get(MULTI_ATTR) or ())


# ---------------------------------------------------------------------------
# the merge: one process row per hop, via the cross-rank machinery
# ---------------------------------------------------------------------------

def _source_trace(wire_spans: list[dict]) -> tuple[dict, float]:
    """One hop's wire spans as a Chrome trace dict with a local epoch —
    exactly the shape ``cluster.merge_traces`` consumes per rank."""
    base = min(s["t0_unix"] for s in wire_spans)
    events = []
    for s in wire_spans:
        args = dict(s.get("attrs") or {})
        args["span_id"] = s.get("span_id")
        if s.get("parent_id") is not None:
            args["parent_id"] = s["parent_id"]
        events.append({
            "ph": "X", "name": s["name"], "pid": 0, "tid": 1,
            "ts": round((s["t0_unix"] - base) * 1e6, 3),
            "dur": round((s["t1_unix"] - s["t0_unix"]) * 1e6, 3),
            "args": args,
        })
    return ({"traceEvents": events, "otherData": {"epoch_unix": base}},
            base)


def merge_request_trace(trace_id: str, sources: dict, *,
                        out_path: str | None = None,
                        offsets_s: dict | None = None,
                        meta: dict | None = None) -> dict:
    """Merge one request's hops into a single Chrome trace.

    ``sources``: {row label: [wire spans]} — e.g. ``{"gateway": [...],
    "r0": [...], "r1": [...]}``; empty lists are dropped. Reuses
    :func:`cluster.merge_traces` (rank merge generalized to string row
    labels) so timestamps are rebased onto one clock-corrected timeline.
    ``meta`` lands in ``otherData`` (failover count, replica hop order,
    suppressed-token count...)."""
    from .cluster import merge_traces

    traces, bases = {}, {}
    for label, spans in sources.items():
        if not spans:
            continue
        traces[label], bases[label] = _source_trace(list(spans))
    if traces:
        doc = merge_traces(traces, offsets_s=offsets_s, bases_unix=bases)
    else:
        doc = {"traceEvents": [], "displayTimeUnit": "ms", "otherData": {}}
    doc["otherData"]["trace_id"] = trace_id
    doc["otherData"]["request_trace"] = True
    for k, v in (meta or {}).items():
        doc["otherData"][k] = v
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, default=str)
    return doc
