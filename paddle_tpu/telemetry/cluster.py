"""Cluster observability plane: cross-rank telemetry over the TCPStore.

Single-process telemetry (metrics registry, span tracer, flight recorder)
answers "what is *this* process doing"; every multi-rank failure mode asks
the harder question — "which rank made the job slow or hung". This module
layers four capabilities over the rendezvous ``TCPStore`` that every
launched job already has:

1. **Aggregation** — each rank runs a :class:`RankPublisher` background
   thread that periodically publishes its metrics JSON snapshot and
   flight-recorder tail under ``telemetry/<rank>/...``; a
   :class:`ClusterAggregator` (rank 0, the launcher, or
   ``tools/cluster_status.py`` attached externally) merges them into one
   fleet view with per-rank (``rank=`` label injected) and rolled-up
   Prometheus/JSON export.
2. **Straggler & hang diagnosis** — ``distributed/collective.py`` reports
   every eager collective through :func:`collective_enter` /
   :func:`collective_exit`; when a publisher is installed these become
   per-rank sequence heartbeats (op, seq#, entered/exited wall stamps) in
   the store. A :class:`ClusterMonitor` detects *desync* (ranks disagree
   on seq#), *stragglers* (a rank persistently the last entrant by more
   than a threshold), and *hangs* (ranks stuck entered while a peer never
   arrived) — and names the rank and collective seq#.
3. **Postmortem bundles** — on ``CollectiveTimeoutError`` (or any caller
   of :func:`trigger_postmortem` / :meth:`ClusterAggregator.collect_postmortem`)
   every rank's publisher answers with its full flight-recorder dump plus
   a Python stack snapshot of all threads (``sys._current_frames``); the
   collector writes them into one ``postmortem-<id>/`` bundle directory —
   the whole-job answer to "who hung", instead of one rank's
   ``flightrec-*.json``.
4. **Cross-rank trace merge** — per-rank Chrome traces carry their
   wall-clock epoch (``tracing.epoch_unix``); :func:`estimate_clock_offset`
   measures each rank's offset against the aggregator's clock with an
   NTP-style min-RTT exchange through the store, and :func:`merge_traces`
   rebases every rank onto one timeline with one process row per rank
   (``trace-merged.json``).

Store key layout (all under the ``telemetry/`` prefix; values are JSON):

    telemetry/<rank>/meta      rank, pid, host, wall, publish_seq,
                               clock_offset_s, trace_epoch_unix
    telemetry/<rank>/metrics   the rank's registry snapshot
    telemetry/<rank>/flight    tail of the rank's flight-recorder ring
    telemetry/<rank>/coll      latest collective heartbeat
                               {seq, op, state, t_enter, t_exit}
    telemetry/clock/req|resp/<rank>/<i>   clock-sync exchange
    telemetry/postmortem/request          {id, reason, from_rank}
    telemetry/postmortem/<id>/rank<r>     per-rank postmortem payload

The ``store`` argument everywhere is duck-typed (``set/get/add/wait``),
so tests can drive the plane with an in-memory fake. IMPORTANT for real
``TCPStore``: a publisher must get its *own* store connection — the wire
protocol is one-request-at-a-time per connection, and the main thread may
sit inside a long ``wait`` (barrier) exactly when the publisher needs to
answer a postmortem request.

Everything here degrades instead of dying: store hiccups during a publish
are counted (``cluster_publish_errors_total``) and retried next tick, and
no hook on the collective hot path costs more than one global load while
no publisher is installed.
"""
from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
import traceback
from dataclasses import dataclass

from . import tracing
from . import history as history_mod
from . import pyprof as pyprof_mod
from .flight_recorder import flight
from .metrics import ENABLED, registry

__all__ = [
    "RankPublisher", "CollectiveHeartbeat", "ClusterAggregator",
    "ClusterMonitor", "ClockResponder", "ClockEstimate",
    "estimate_clock_offset", "merge_traces", "stack_snapshot",
    "collective_enter", "collective_exit", "trigger_postmortem",
    "publisher", "start_from_env", "STORE_ENV",
]

# the launcher advertises the telemetry store endpoint to workers here
STORE_ENV = "PADDLE_TELEMETRY_STORE"

PREFIX = "telemetry"
PM_REQUEST_KEY = f"{PREFIX}/postmortem/request"


def _k(rank: int, leaf: str) -> str:
    return f"{PREFIX}/{rank}/{leaf}"


def _k_pm(pm_id: str, rank: int) -> str:
    return f"{PREFIX}/postmortem/{pm_id}/rank{rank}"


def _set_json(store, key: str, obj) -> None:
    store.set(key, json.dumps(obj, default=str).encode())


def _get_json(store, key: str):
    raw = store.get(key)
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return None


def _cluster_metrics():
    reg = registry()
    return (
        reg.counter("cluster_publish_total",
                    "per-rank telemetry snapshots published to the store"),
        reg.counter("cluster_publish_errors_total",
                    "publish ticks that hit a store error (retried)"),
        reg.gauge("cluster_seq_spread",
                  "max-min collective seq# across ranks (monitor view)"),
        reg.counter("cluster_straggle_events_total",
                    "collectives a rank entered last by > threshold",
                    ("rank",)),
    )


_M_PUBLISH, _M_PUB_ERRS, _M_SPREAD, _M_STRAGGLE = _cluster_metrics()


# ---------------------------------------------------------------------------
# stack snapshots (the postmortem payload's "where was everyone" half)
# ---------------------------------------------------------------------------

def stack_snapshot() -> dict:
    """Every live thread's Python stack, formatted (faulthandler's view,
    as JSON-able strings). Never raises — a postmortem helper that crashes
    the process it is autopsying is worse than no snapshot."""
    out = {}
    try:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            label = f"{names.get(ident, 'thread')}-{ident}"
            out[label] = [ln.rstrip("\n")
                          for ln in traceback.format_stack(frame)]
    except Exception:  # lint: allow-silent(stack snapshot never raises; partial dump beats none)
        pass
    return out


# ---------------------------------------------------------------------------
# clock sync (NTP-style, through the store)
# ---------------------------------------------------------------------------

@dataclass
class ClockEstimate:
    """offset_s: add to THIS rank's wall clock to get the responder's
    (master) clock. rtt_s: round-trip of the best (kept) probe."""

    offset_s: float
    rtt_s: float
    probes: int


def estimate_clock_offset(store, rank: int, probes: int = 5,
                          timeout_s: float = 10.0, poll_s: float = 0.002,
                          clock=time.time) -> ClockEstimate:
    """Measure this rank's wall-clock offset against the aggregator's
    :class:`ClockResponder` with ``probes`` request/response round trips
    through the store, keeping the minimum-RTT sample (the standard NTP
    argument: the shortest round trip bounds the asymmetry error).
    Polling ``get`` rather than ``wait`` keeps the store connection free
    for other threads between polls."""
    best = None
    deadline = time.monotonic() + timeout_s
    for i in range(probes):
        t0 = clock()
        _set_json(store, f"{PREFIX}/clock/req/{rank}/{i}", {"t0": t0})
        resp = None
        while resp is None:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"clock sync: no responder answered rank {rank} probe "
                    f"{i} within {timeout_s}s (is a ClockResponder running "
                    "on the aggregator?)")
            resp = _get_json(store, f"{PREFIX}/clock/resp/{rank}/{i}")
            if resp is None:
                time.sleep(poll_s)
        t1 = clock()
        rtt = t1 - t0
        offset = float(resp["t_server"]) - (t0 + t1) / 2.0
        if best is None or rtt < best[0]:
            best = (rtt, offset)
    return ClockEstimate(offset_s=best[1], rtt_s=best[0], probes=probes)


class ClockResponder:
    """Aggregator-side half of the exchange: a thread that answers every
    rank's ``clock/req`` with the responder's wall time."""

    def __init__(self, store, world_size: int, poll_s: float = 0.002,
                 clock=time.time):
        self.store = store
        self.world_size = int(world_size)
        self.poll_s = poll_s
        self._clock = clock
        self._next = [0] * self.world_size   # per-rank next unanswered probe
        self._stop = threading.Event()
        self._thread = None
        self.answered = 0

    def serve_once(self) -> int:
        """Answer every currently-pending probe; returns how many."""
        n = 0
        for r in range(self.world_size):
            while True:
                i = self._next[r]
                req = _get_json(self.store, f"{PREFIX}/clock/req/{r}/{i}")
                if req is None:
                    break
                _set_json(self.store, f"{PREFIX}/clock/resp/{r}/{i}",
                          {"t_server": self._clock()})
                self._next[r] = i + 1
                n += 1
        self.answered += n
        return n

    def start(self):
        def run():
            while not self._stop.wait(self.poll_s):
                try:
                    self.serve_once()
                except Exception:  # lint: allow-silent(transient store error; retry next tick)
                    pass
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="cluster-clock-responder")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()


# ---------------------------------------------------------------------------
# collective heartbeats (the straggler/hang signal)
# ---------------------------------------------------------------------------

class CollectiveHeartbeat:
    """Per-rank collective sequence heartbeat: every instrumented
    collective bumps ``seq`` and publishes (op, seq, entered/exited wall
    stamps) to ``telemetry/<rank>/coll``. Store failures never propagate
    into the collective — they are counted and the heartbeat goes stale,
    which the monitor surfaces as publish age."""

    def __init__(self, store, rank: int, clock=time.time):
        self.store = store
        self.rank = int(rank)
        self.seq = 0
        self.errors = 0
        self._clock = clock
        self._cur = None

    def enter(self, op: str, **info):
        self.seq += 1
        self._cur = {"rank": self.rank, "seq": self.seq, "op": op,
                     "state": "entered", "t_enter": self._clock(),
                     "t_exit": None, **info}
        self._publish()

    def exit(self, op: str):
        if self._cur is None or self._cur["op"] != op:
            return
        self._cur["state"] = "exited"
        self._cur["t_exit"] = self._clock()
        self._publish()

    def _publish(self):
        try:
            _set_json(self.store, _k(self.rank, "coll"), self._cur)
        except Exception:
            self.errors += 1


# ---------------------------------------------------------------------------
# the per-rank publisher
# ---------------------------------------------------------------------------

class RankPublisher:
    """Background thread publishing this rank's telemetry to the store
    every ``interval_s``: metrics snapshot, flight-recorder tail, and a
    meta record (publish seq, clock offset, trace epoch). Between ticks it
    also watches ``telemetry/postmortem/request`` and answers with this
    rank's flight dump + stack snapshot — which is what lets a postmortem
    bundle contain *every* rank even while rank main threads are wedged
    inside a collective.

    Give it a dedicated store connection (see module docstring).
    ``clock=`` exists so tests (and the chaos straggler suite) can model
    host clock skew deterministically."""

    def __init__(self, store, rank: int, world_size: int, *,
                 interval_s: float = 1.0, flight_tail: int = 128,
                 clock=time.time, sync_clock: bool = True,
                 clock_probes: int = 5, profile_top_n: int = 200):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.interval_s = float(interval_s)
        self.flight_tail = int(flight_tail)
        self.profile_top_n = int(profile_top_n)
        self._clock = clock
        self.sync_clock = sync_clock
        self.clock_probes = int(clock_probes)
        self.clock_estimate: ClockEstimate | None = None
        self.heartbeat = CollectiveHeartbeat(store, self.rank, clock=clock)
        self.publish_seq = 0
        self._answered_pm: set[str] = set()
        self._pm_ids = 0
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "RankPublisher":
        """Sync the clock (when a responder is up), publish once, install
        as the process publisher (collective hooks activate), and start
        the periodic thread."""
        if self.sync_clock:
            try:
                self.clock_estimate = estimate_clock_offset(
                    self.store, self.rank, probes=self.clock_probes,
                    clock=self._clock)
            except Exception:  # lint: allow-silent(no clock responder; offsets recorded as unknown)
                self.clock_estimate = None
        self.publish_once()
        install(self)

        def run():
            while not self._stop.wait(self.interval_s):
                self.publish_once()

        self._thread = threading.Thread(
            target=run, daemon=True, name=f"cluster-publisher-{self.rank}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
        if publisher() is self:
            install(None)

    # -- publishing ------------------------------------------------------
    def publish_once(self):
        """One tick: meta + metrics snapshot + flight tail, then answer
        any outstanding postmortem request. Never raises."""
        try:
            self.publish_seq += 1
            off = self.clock_estimate
            _set_json(self.store, _k(self.rank, "meta"), {
                "rank": self.rank,
                "world_size": self.world_size,
                "pid": os.getpid(),
                "host": socket.gethostname(),
                "wall": self._clock(),
                "publish_seq": self.publish_seq,
                "interval_s": self.interval_s,
                "clock_offset_s": off.offset_s if off else None,
                "clock_rtt_s": off.rtt_s if off else None,
                "trace_epoch_unix": self.trace_epoch_unix(),
            })
            _set_json(self.store, _k(self.rank, "metrics"),
                      registry().snapshot())
            _set_json(self.store, _k(self.rank, "flight"),
                      flight().events()[-self.flight_tail:])
            prof = pyprof_mod.installed()
            if prof is not None:
                # folded top-N rides the heartbeat: the aggregator's
                # fleet-wide flame view is just a sum over these
                _set_json(self.store, _k(self.rank, "pyprof"), {
                    "rank": self.rank,
                    "hz": prof.hz,
                    "samples": prof.samples,
                    "overhead_frac": prof.overhead_frac(),
                    "folded": prof.folded_dict(self.profile_top_n),
                })
            _M_PUBLISH.inc()
        except Exception:
            _M_PUB_ERRS.inc()
        try:
            self._check_postmortem()
        except Exception:
            _M_PUB_ERRS.inc()

    def trace_epoch_unix(self) -> float:
        """Wall time (on THIS publisher's clock) of this process's trace
        ``ts=0`` — the per-rank base :func:`merge_traces` aligns on."""
        return self._clock() - (time.monotonic() - tracing._EPOCH)

    # -- postmortem ------------------------------------------------------
    def _check_postmortem(self):
        req = _get_json(self.store, PM_REQUEST_KEY)
        if not req or req.get("id") in self._answered_pm:
            return
        self._answered_pm.add(req["id"])
        self.answer_postmortem(req["id"], req.get("reason", ""))

    def answer_postmortem(self, pm_id: str, reason: str = ""):
        evs = flight().events()
        hist = history_mod.installed()
        prof = pyprof_mod.installed()
        _set_json(self.store, _k_pm(pm_id, self.rank), {
            "rank": self.rank,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "wall": self._clock(),
            "reason": reason,
            "stacks": stack_snapshot(),
            "flight": {"num_events": len(evs), "events": evs},
            "coll": {"seq": self.heartbeat.seq},
            # "what was happening the last N minutes before it died" —
            # the history last-window slice, when a store is installed
            "history": hist.last_window() if hist is not None else None,
            "pyprof": ({"hz": prof.hz, "samples": prof.samples,
                        "folded": prof.folded_dict(self.profile_top_n)}
                       if prof is not None else None),
        })

    def trigger_postmortem(self, reason: str) -> str:
        """Broadcast a postmortem request (every rank's publisher answers,
        including this one, immediately). Returns the request id; a
        collector (:meth:`ClusterAggregator.collect_postmortem` or the
        launcher) turns the answers into a bundle directory."""
        self._pm_ids += 1
        pm_id = f"{self.rank}-{self._pm_ids}-{int(self._clock() * 1000)}"
        _set_json(self.store, PM_REQUEST_KEY,
                  {"id": pm_id, "reason": reason, "from_rank": self.rank,
                   "wall": self._clock()})
        self._answered_pm.add(pm_id)
        try:
            self.answer_postmortem(pm_id, reason)
        except Exception:
            _M_PUB_ERRS.inc()
        return pm_id


# ---------------------------------------------------------------------------
# process-global publisher + the collective.py hooks
# ---------------------------------------------------------------------------

_PUBLISHER: RankPublisher | None = None


def publisher() -> RankPublisher | None:
    return _PUBLISHER


def install(pub: RankPublisher | None):
    """Make ``pub`` the process publisher (collective heartbeats activate;
    ``install(None)`` deactivates)."""
    global _PUBLISHER
    _PUBLISHER = pub


def collective_enter(op: str, **info):
    """Hot-path hook compiled into ``distributed/collective.py``: one
    global load when no publisher is installed."""
    p = _PUBLISHER
    if p is not None and ENABLED[0]:
        p.heartbeat.enter(op, **info)


def collective_exit(op: str):
    p = _PUBLISHER
    if p is not None and ENABLED[0]:
        p.heartbeat.exit(op)


def trigger_postmortem(reason: str) -> str | None:
    """Fleet-wide postmortem request, no-op without a publisher (the
    single-process flight-recorder dump still happens at the call site)."""
    p = _PUBLISHER
    if p is None:
        return None
    try:
        return p.trigger_postmortem(reason)
    except Exception:  # lint: allow-silent(best-effort postmortem; None = no publisher installed)
        return None


def start_from_env(store=None, **kwargs) -> RankPublisher | None:
    """Start a publisher from the launcher-provided environment
    (``$PADDLE_TELEMETRY_STORE`` plus the standard rank/world variables);
    None (and no side effects) when the env does not ask for one. Worker
    scripts call this once at startup — ``resilience/demo.py`` shows the
    pattern."""
    endpoint = os.environ.get(STORE_ENV)
    if not endpoint:
        return None
    rank = int(os.environ.get("PADDLE_TPU_PROCESS_ID")
               or os.environ.get("PADDLE_TRAINER_ID") or 0)
    world = int(os.environ.get("PADDLE_TPU_NUM_PROCESSES")
                or os.environ.get("PADDLE_TRAINERS_NUM") or 1)
    if store is None:
        from ..distributed.tcp_store import TCPStore

        host, _, port = endpoint.rpartition(":")
        store = TCPStore(host or "127.0.0.1", int(port))
    return RankPublisher(store, rank, world, **kwargs).start()


# ---------------------------------------------------------------------------
# the monitor (straggler / desync / hang diagnosis)
# ---------------------------------------------------------------------------

class ClusterMonitor:
    """Reads every rank's collective heartbeat and meta records and turns
    them into a diagnosis:

    - **desync**: ranks disagree on the collective seq# by
      ``desync_threshold`` or more — someone skipped or double-counted a
      collective, the precursor to a deadlock.
    - **straggler**: for each seq# where every rank's enter stamp is
      known, the last entrant's lag over the fleet median (clock-offset
      corrected) exceeds ``straggler_threshold_s``; a rank scored on
      ``straggler_min_seqs`` distinct seq#s is *named*.
    - **hang**: some ranks have sat in state ``entered`` for longer than
      ``hang_threshold_s`` — the suspects are the ranks *behind* them
      (lower seq#, never arrived); if every rank entered, the interconnect
      itself is the suspect.

    Wall stamps are corrected with each rank's published
    ``clock_offset_s`` so cross-host skew does not fabricate stragglers.
    """

    def __init__(self, store, world_size: int, *,
                 straggler_threshold_s: float = 0.2,
                 straggler_min_seqs: int = 3,
                 desync_threshold: int = 2,
                 hang_threshold_s: float = 5.0,
                 clock=time.time):
        self.store = store
        self.world_size = int(world_size)
        self.straggler_threshold_s = float(straggler_threshold_s)
        self.straggler_min_seqs = int(straggler_min_seqs)
        self.desync_threshold = int(desync_threshold)
        self.hang_threshold_s = float(hang_threshold_s)
        self._clock = clock
        self._offsets: dict[int, float] = {}
        self._enters: dict[int, dict[int, float]] = {}   # seq -> rank -> t
        self._enter_ops: dict[int, str] = {}             # seq -> op
        self._scored: set[int] = set()
        self.straggles: dict[int, list[tuple[int, float]]] = {}

    # -- raw reads -------------------------------------------------------
    def _read(self, rank: int, leaf: str):
        try:
            return _get_json(self.store, _k(rank, leaf))
        except Exception:  # lint: allow-silent(unreachable rank reads as absent; staleness is surfaced upstream)
            return None

    def offset(self, rank: int) -> float:
        return self._offsets.get(rank, 0.0)

    # -- one diagnosis pass ----------------------------------------------
    def poll(self) -> dict:
        now = self._clock()
        ranks = {}
        seqs = {}
        for r in range(self.world_size):
            meta = self._read(r, "meta")
            if meta and meta.get("clock_offset_s") is not None:
                self._offsets[r] = float(meta["clock_offset_s"])
            coll = self._read(r, "coll")
            off = self.offset(r)
            seq = int(coll["seq"]) if coll else 0
            seqs[r] = seq
            t_enter = (float(coll["t_enter"]) + off
                       if coll and coll.get("t_enter") is not None else None)
            ranks[r] = {
                "seq": seq,
                "op": coll["op"] if coll else None,
                "state": coll["state"] if coll else "never-reported",
                "t_enter": t_enter,
                "in_state_s": (now - t_enter if t_enter is not None
                               and coll["state"] == "entered" else None),
                "publish_age_s": (now - (float(meta["wall"]) + off)
                                  if meta else None),
                "clock_offset_s": self._offsets.get(r),
            }
            if coll and coll.get("t_enter") is not None:
                self._enters.setdefault(seq, {})[r] = t_enter
                self._enter_ops.setdefault(seq, coll.get("op"))
        self._score()
        spread = (max(seqs.values()) - min(seqs.values())) if seqs else 0
        _M_SPREAD.set(spread)
        min_seq = min(seqs.values()) if seqs else 0
        max_seq = max(seqs.values()) if seqs else 0
        behind = sorted(r for r, s in seqs.items() if spread and s == min_seq)
        report = {
            "wall": now,
            "world_size": self.world_size,
            "ranks": ranks,
            "seq_spread": spread,
            "desync": spread >= self.desync_threshold,
            "behind_ranks": behind,
            "straggler": self._named_straggler(),
            "hang": self._diagnose_hang(ranks, behind, max_seq),
        }
        return report

    def _score(self):
        """Score every seq# whose full enter-time set is now known (enters
        accumulate across polls, so a fast poll loop never misses one)."""
        for seq, enters in self._enters.items():
            if seq in self._scored or len(enters) < self.world_size:
                continue
            self._scored.add(seq)
            ts = sorted(enters.values())
            median = ts[len(ts) // 2]
            worst_rank = max(enters, key=lambda r: enters[r])
            lag = enters[worst_rank] - median
            if lag > self.straggler_threshold_s:
                self.straggles.setdefault(worst_rank, []).append((seq, lag))
                _M_STRAGGLE.labels(rank=str(worst_rank)).inc()

    def _named_straggler(self):
        for rank, hits in sorted(self.straggles.items(),
                                 key=lambda kv: -len(kv[1])):
            if len(hits) >= self.straggler_min_seqs:
                lags = [lag for _, lag in hits]
                return {
                    "rank": rank,
                    "seqs": [s for s, _ in hits],
                    "ops": {s: self._enter_ops.get(s) for s, _ in hits},
                    "mean_lag_s": sum(lags) / len(lags),
                    "last_seq": hits[-1][0],
                }
        return None

    def _diagnose_hang(self, ranks: dict, behind: list, max_seq: int):
        waiting = sorted(
            r for r, v in ranks.items()
            if v["in_state_s"] is not None
            and v["in_state_s"] > self.hang_threshold_s)
        if not waiting:
            return {"hung": False, "suspect_ranks": [], "waiting_ranks": [],
                    "stuck_for_s": 0.0}
        suspects = [r for r in behind if r not in waiting] or behind
        if not suspects:
            # everyone arrived and nobody finished: blame the transport
            suspects = waiting
        return {
            "hung": True,
            "suspect_ranks": sorted(suspects),
            "waiting_ranks": waiting,
            "waiting_seq": max_seq,
            "waiting_op": next((ranks[r]["op"] for r in waiting), None),
            "stuck_for_s": max(ranks[r]["in_state_s"] for r in waiting),
        }


# ---------------------------------------------------------------------------
# the aggregator (fleet view, merged export, postmortem collection)
# ---------------------------------------------------------------------------

class ClusterAggregator:
    """Rank-0 / external-tool side: merge every rank's published telemetry
    into one fleet view and collect postmortem bundles."""

    def __init__(self, store, world_size: int, clock=time.time):
        self.store = store
        self.world_size = int(world_size)
        self._clock = clock
        self.responder: ClockResponder | None = None

    # -- clock -----------------------------------------------------------
    def start_clock_responder(self) -> ClockResponder:
        self.responder = ClockResponder(self.store, self.world_size,
                                        clock=self._clock).start()
        return self.responder

    def stop(self):
        if self.responder is not None:
            self.responder.stop()
            self.responder = None

    # -- fleet view ------------------------------------------------------
    def fleet_view(self) -> dict:
        """Everything every rank last published, raw."""
        ranks = {}
        for r in range(self.world_size):
            ranks[r] = {
                "meta": _get_json(self.store, _k(r, "meta")),
                "metrics": _get_json(self.store, _k(r, "metrics")),
                "flight": _get_json(self.store, _k(r, "flight")),
                "coll": _get_json(self.store, _k(r, "coll")),
                "pyprof": _get_json(self.store, _k(r, "pyprof")),
            }
        return {"collected_wall": self._clock(),
                "world_size": self.world_size, "ranks": ranks}

    def merged_snapshot(self) -> dict:
        """One registry-snapshot-shaped dict for the whole fleet: every
        per-rank series gains a ``rank`` label, and each family gets a
        ``rollup`` (counters/histograms summed; gauges sum/min/max) —
        the fleet-level view a dashboard wants next to the per-rank one."""
        out = {"__meta__": {"wall_time": self._clock(),
                            "world_size": self.world_size, "merged": True}}
        for r in range(self.world_size):
            snap = _get_json(self.store, _k(r, "metrics"))
            if not snap:
                continue
            for name, fam in snap.items():
                if name.startswith("__"):
                    continue
                dst = out.setdefault(name, {
                    "type": fam["type"], "help": fam.get("help", ""),
                    "labels": ["rank"] + list(fam.get("labels", [])),
                    "series": [], "rollup": None,
                })
                for s in fam["series"]:
                    s2 = dict(s)
                    s2["labels"] = {"rank": str(r), **s.get("labels", {})}
                    dst["series"].append(s2)
        for name, fam in out.items():
            if name.startswith("__"):
                continue
            fam["rollup"] = self._rollup(fam)
        return out

    @staticmethod
    def _rollup(fam: dict):
        kind, series = fam["type"], fam["series"]
        if not series:
            return None
        if kind == "histogram":
            buckets: dict[str, int] = {}
            total_sum, total_count = 0.0, 0
            for s in series:
                for edge, c in s.get("buckets", {}).items():
                    buckets[edge] = buckets.get(edge, 0) + int(c)
                total_sum += float(s.get("sum", 0.0))
                total_count += int(s.get("count", 0))
            return {"buckets": buckets, "sum": total_sum,
                    "count": total_count,
                    "mean": total_sum / total_count if total_count else None}
        vals = [float(s.get("value", 0.0)) for s in series]
        if kind == "counter":
            return {"value": sum(vals)}
        return {"sum": sum(vals), "min": min(vals), "max": max(vals)}

    def merged_profile(self) -> dict:
        """The fleet-wide flame view: every rank's published folded
        profile summed stack-wise (stacks are rooted at thread names, so
        identical subsystems across ranks merge into one frame tower).
        ``{"stacks": {stack: count}, "ranks": {r: {hz, samples,
        overhead_frac}}, "total_samples": N}``."""
        tables, ranks = [], {}
        for r in range(self.world_size):
            p = _get_json(self.store, _k(r, "pyprof"))
            if not p:
                continue
            tables.append(p.get("folded") or {})
            ranks[r] = {"hz": p.get("hz"), "samples": p.get("samples"),
                        "overhead_frac": p.get("overhead_frac")}
        stacks = pyprof_mod.merge_folded(*tables)
        return {"stacks": stacks, "ranks": ranks,
                "total_samples": sum(stacks.values()),
                "collected_wall": self._clock()}

    def merged_folded_text(self) -> str:
        """The merged view as folded flamegraph lines (pipe to a
        renderer, or reload with ``pyprof.parse_folded``)."""
        prof = self.merged_profile()
        return "\n".join(f"{k} {v}" for k, v in prof["stacks"].items())

    def prometheus_text(self) -> str:
        """Fleet exposition: every rank's series with the ``rank`` label
        injected (rollups are the scraper's `sum by`—only the raw series
        are emitted)."""
        merged = self.merged_snapshot()
        lines = []
        for name in sorted(k for k in merged if not k.startswith("__")):
            fam = merged[name]
            if fam.get("help"):
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for s in fam["series"]:
                base = ",".join(f'{k}="{v}"'
                                for k, v in s["labels"].items())
                if fam["type"] == "histogram":
                    for edge, c in sorted(s.get("buckets", {}).items(),
                                          key=lambda kv: float(kv[0])):
                        lines.append(
                            f'{name}_bucket{{{base},le="{edge}"}} {c}')
                    lines.append(f'{name}_bucket{{{base},le="+Inf"}} '
                                 f'{s.get("count", 0)}')
                    lines.append(f'{name}_sum{{{base}}} {s.get("sum", 0)}')
                    lines.append(
                        f'{name}_count{{{base}}} {s.get("count", 0)}')
                else:
                    lines.append(f'{name}{{{base}}} {s.get("value", 0)}')
        return "\n".join(lines) + ("\n" if lines else "")

    # -- postmortem bundles ----------------------------------------------
    def collect_postmortem(self, reason: str, out_dir: str | None = None,
                           timeout_s: float = 10.0, poll_s: float = 0.05,
                           pm_id: str | None = None) -> str | None:
        """Broadcast a postmortem request (unless ``pm_id`` names one
        already triggered, e.g. by the rank whose collective timed out)
        and gather every rank's answer into a bundle directory::

            postmortem-<id>/
              manifest.json            reason, ranks collected/missing
              rank<r>-flight.json      that rank's flight-recorder dump
              rank<r>-stacks.txt       all of its threads' Python stacks
              rank<r>-history.json     metrics-history last-window slice
                                       (when that rank had a store)
              rank<r>-pyprof.folded    folded CPU profile (when that rank
                                       had a profiler)

        Ranks that never answer within ``timeout_s`` are listed in the
        manifest's ``missing`` — a dead process is itself a finding.
        Returns the bundle path (None only if even the bundle dir could
        not be written)."""
        if pm_id is None:
            pm_id = f"agg-{os.getpid()}-{int(self._clock() * 1000)}"
            _set_json(self.store, PM_REQUEST_KEY,
                      {"id": pm_id, "reason": reason, "from_rank": None,
                       "wall": self._clock()})
        payloads: dict[int, dict] = {}
        deadline = time.monotonic() + timeout_s
        while (len(payloads) < self.world_size
               and time.monotonic() < deadline):
            for r in range(self.world_size):
                if r in payloads:
                    continue
                p = _get_json(self.store, _k_pm(pm_id, r))
                if p is not None:
                    payloads[r] = p
            if len(payloads) < self.world_size:
                time.sleep(poll_s)
        try:
            root = out_dir or os.environ.get("PADDLE_TPU_FLIGHT_DIR") or \
                __import__("tempfile").gettempdir()
            bundle = os.path.join(root, f"postmortem-{pm_id}")
            os.makedirs(bundle, exist_ok=True)
            for r, p in payloads.items():
                with open(os.path.join(bundle, f"rank{r}-flight.json"),
                          "w") as f:
                    json.dump({k: v for k, v in p.items()
                               if k not in ("stacks", "history", "pyprof")},
                              f, indent=1, default=str)
                with open(os.path.join(bundle, f"rank{r}-stacks.txt"),
                          "w") as f:
                    for label, frames in p.get("stacks", {}).items():
                        f.write(f"== {label} ==\n")
                        f.write("\n".join(frames) + "\n\n")
                if p.get("history"):
                    with open(os.path.join(bundle,
                                           f"rank{r}-history.json"),
                              "w") as f:
                        json.dump(p["history"], f, indent=1, default=str)
                if p.get("pyprof"):
                    with open(os.path.join(bundle,
                                           f"rank{r}-pyprof.folded"),
                              "w") as f:
                        folded = p["pyprof"].get("folded") or {}
                        f.write("\n".join(f"{k} {v}"
                                          for k, v in folded.items()))
                        f.write("\n")
            with open(os.path.join(bundle, "manifest.json"), "w") as f:
                json.dump({
                    "id": pm_id,
                    "reason": reason,
                    "wall": self._clock(),
                    "world_size": self.world_size,
                    "ranks_collected": sorted(payloads),
                    "missing": [r for r in range(self.world_size)
                                if r not in payloads],
                    "ranks_with_history": sorted(
                        r for r, p in payloads.items() if p.get("history")),
                    "ranks_with_profile": sorted(
                        r for r, p in payloads.items() if p.get("pyprof")),
                }, f, indent=1)
            return bundle
        except Exception:  # lint: allow-silent(aggregation is best-effort; None = bundle unavailable)
            return None


# ---------------------------------------------------------------------------
# cross-rank trace merge
# ---------------------------------------------------------------------------

def merge_traces(traces: dict, out_path: str | None = None,
                 offsets_s: dict | None = None,
                 bases_unix: dict | None = None) -> dict:
    """Merge per-source Chrome traces onto one timeline, one process row
    per source.

    ``traces``: {source: path-or-trace-dict}. A source is a rank (int, or
    a numeric string — the original use) or any string label (a serving
    replica id in a per-request merge, ``telemetry.reqtrace``). Each
    source's events are shifted by ``(epoch_unix_s + offset_s) - min over
    sources`` so the earliest source's first microsecond is ts 0 and every
    other source lands at its true (clock-corrected) wall position.
    ``bases_unix`` overrides the per-trace ``otherData.epoch_unix`` (the
    publishers' meta records carry the authoritative value, measured on
    the same clock the offsets were estimated against). ``offsets_s[s]``
    is source s's :class:`ClockEstimate` ``offset_s``. Returns the merged
    trace dict (and writes it to ``out_path`` when given)."""
    offsets_s = offsets_s or {}
    bases_unix = bases_unix or {}
    loaded = {}
    for src, t in traces.items():
        if isinstance(t, str):
            with open(t) as f:
                t = json.load(f)
        try:
            key = int(src)
        except (TypeError, ValueError):
            key = str(src)
        loaded[key] = t

    def _get(d, key):
        if key in d:
            return d[key]
        return d.get(str(key))

    bases = {}
    for key, t in loaded.items():
        base = _get(bases_unix, key)
        if base is None:
            base = float(t.get("otherData", {}).get("epoch_unix", 0.0))
        bases[key] = base + float(_get(offsets_s, key) or 0.0)
    t_zero = min(bases.values()) if bases else 0.0
    # ranks keep their numeric pid and "rank N" label; string sources get
    # sequential pids after the ranks and their label verbatim
    int_keys = sorted(k for k in loaded if isinstance(k, int))
    str_keys = sorted((k for k in loaded if isinstance(k, str)), key=str)
    next_pid = (max(int_keys) + 1) if int_keys else 0
    order, pids, names = [], {}, {}
    for k in int_keys:
        order.append(k)
        pids[k] = k
        names[k] = f"rank {k}"
    for i, k in enumerate(str_keys):
        order.append(k)
        pids[k] = next_pid + i
        names[k] = k
    events = []
    for idx, key in enumerate(order):
        pid = pids[key]
        shift_us = (bases[key] - t_zero) * 1e6
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": names[key]}})
        events.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                       "args": {"sort_index": idx}})
        for e in loaded[key].get("traceEvents", []):
            e2 = dict(e)
            e2["pid"] = pid
            if "ts" in e2:
                e2["ts"] = round(float(e2["ts"]) + shift_us, 3)
            events.append(e2)
    events.sort(key=lambda e: (e.get("ts", -1), e.get("pid", 0)))
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged": True,
            "ranks": order,
            "sources": {str(k): names[k] for k in order},
            "t_zero_unix": t_zero,
            "clock_offsets_s": {str(k): _get(offsets_s, k) or 0.0
                                for k in loaded},
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, default=str)
    return doc


# ---------------------------------------------------------------------------
# demo worker (chaos_run --suite straggler and the spawned tests)
# ---------------------------------------------------------------------------

def demo_worker():  # pragma: no cover - subprocess entry, tested end-to-end
    """Subprocess entry for the straggler/hang demo: N ranks run a loop of
    instrumented pseudo-collectives (store barrier = the synchronization;
    heartbeats, spans, and the fault site ``collective.step`` = the
    observable surface). Configured entirely from env:

        PADDLE_TELEMETRY_STORE  host:port of the master store
        DEMO_RANK / DEMO_WORLD  this rank / world size
        DEMO_STEPS              collectives to run (default 6)
        DEMO_SCENARIO           key prefix isolating concurrent runs
        DEMO_CLOCK_SKEW         seconds added to this rank's wall clock
                                (models cross-host clock skew)
        DEMO_TRACE_OUT          export this rank's Chrome trace here
        FLAGS_fault_plan        e.g. collective:delay=0.3x* on ONE rank
                                makes it the straggler the monitor must
                                name
    """
    from ..distributed.tcp_store import TCPStore
    from ..utils import faults
    from . import span, tracer

    endpoint = os.environ[STORE_ENV]
    host, _, port = endpoint.rpartition(":")
    rank = int(os.environ["DEMO_RANK"])
    world = int(os.environ["DEMO_WORLD"])
    steps = int(os.environ.get("DEMO_STEPS", "6"))
    scen = os.environ.get("DEMO_SCENARIO", "demo")
    skew = float(os.environ.get("DEMO_CLOCK_SKEW", "0") or 0)
    trace_out = os.environ.get("DEMO_TRACE_OUT")
    # lint: allow-wallclock(demo deliberately skews the published wall clock)
    clock = (lambda: time.time() + skew) if skew else time.time

    store_main = TCPStore(host or "127.0.0.1", int(port))
    store_pub = TCPStore(host or "127.0.0.1", int(port))  # dedicated conn
    pub = RankPublisher(store_pub, rank, world, interval_s=0.05,
                        clock=clock).start()
    try:
        for i in range(steps):
            with span("demo.step", step=i, rank=rank):
                # "compute" before the collective — the straggler's delay
                # fires here, so it arrives late, exactly like a slow rank
                faults.inject("collective.step", rank=rank, step=i)
                collective_enter("demo_all_reduce", nranks=world)
                store_main.barrier(f"{scen}/step{i}", world, timeout=120.0)
                collective_exit("demo_all_reduce")
        pub.publish_once()
        if trace_out:
            tracer().export_chrome(trace_out)
        store_main.set(_k(rank, "done"), b"1")
        # linger so late postmortem requests still get an answer
        time.sleep(float(os.environ.get("DEMO_LINGER_S", "0.5")))
    finally:
        pub.stop()
        store_main.close()
        store_pub.close()
