"""Continuous sampling profiler: where wall-clock time actually goes.

A daemon thread (``telemetry-pyprof``) wakes at ``hz`` and snapshots
``sys._current_frames()`` — every live thread's Python stack — and
aggregates them into a bounded ``stack -> sample count`` table. Stacks
are keyed **root-first by thread name** (the PR-16 lint pass guarantees
every background thread in this repo is named: ``serving-engine-0``,
``router-probe``, ``journal-compactor``, ...), so the profile reads as
one flamegraph per subsystem with zero symbol munging:

    serving-engine-0;engine.py:step;attention.py:paged_attn   412
    telemetry-history-sampler;history.py:sample_once           9

Two export formats, both dependency-free: folded flamegraph lines
(:meth:`SamplingProfiler.folded` — pipe into any flamegraph renderer)
and speedscope JSON (:meth:`SamplingProfiler.speedscope` — drag onto
https://speedscope.app). The sampler's own cost is self-measured and
exported (``pyprof_overhead_frac``: sampling busy-time over elapsed
time) and gated end to end by ``tools/perf_gate.py``
(``profiler_overhead_frac``: serving throughput profiler-off vs -on).

Fleet view: when a profiler is :func:`install`-ed, the cluster
``RankPublisher`` ships its folded top-N with every heartbeat and
``ClusterAggregator.merged_profile()`` sums identical stacks across
ranks — one flame view for the whole fleet (``cluster_status.py
--profile``, gateway ``/v1/profile``).
"""
from __future__ import annotations

import os
import sys
import threading
import time

from .metrics import ENABLED, registry
from ..analysis import locksan

__all__ = ["SamplingProfiler", "install", "installed", "uninstall",
           "merge_folded", "parse_folded"]

_M = [None]


def _m():
    if _M[0] is None:
        reg = registry()
        class NS:
            samples = reg.counter(
                "pyprof_samples_total", "profiler sampling ticks")
            stacks_seen = reg.counter(
                "pyprof_stack_samples_total",
                "thread-stack observations aggregated")
            distinct = reg.gauge(
                "pyprof_distinct_stacks", "distinct stacks in the table")
            threads = reg.gauge(
                "pyprof_threads", "threads seen in the last sample")
            dropped = reg.counter(
                "pyprof_stacks_dropped_total",
                "stack observations rejected by the max_stacks cap")
            sample_s = reg.histogram(
                "pyprof_sample_seconds", "wall cost of one sampling tick",
                buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                         0.01, 0.025))
            overhead = reg.gauge(
                "pyprof_overhead_frac",
                "profiler busy-time fraction since start (self-measured)")
        _M[0] = NS
    return _M[0]


def _frame_name(frame) -> str:
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class SamplingProfiler:
    """Aggregating wall-clock sampler over ``sys._current_frames()``."""

    def __init__(self, hz: float = 29.0, *, max_stacks: int = 4096,
                 max_depth: int = 64, clock=time.monotonic):
        if hz <= 0:
            raise ValueError(f"hz must be positive, got {hz}")
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self.clock = clock
        self._counts: dict[str, int] = {}
        self._lock = locksan.Lock("pyprof.table")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started_t: float | None = None
        self._busy_s = 0.0
        self.samples = 0
        self.stack_samples = 0

    # -- sampling ----------------------------------------------------------
    def sample_once(self) -> int:
        """Snapshot every thread's stack into the table once. Returns the
        number of thread-stacks recorded."""
        t0 = time.perf_counter()
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        recorded = 0
        rows = []
        for ident, frame in frames.items():
            if ident == me:
                continue  # the profiler profiling itself is pure noise
            parts = []
            f = frame
            while f is not None and len(parts) < self.max_depth:
                parts.append(_frame_name(f))
                f = f.f_back
            parts.append(names.get(ident, f"thread-{ident}"))
            parts.reverse()  # root (thread name) first, leaf last
            rows.append(";".join(parts))
        del frames  # drop frame refs promptly
        m = _m()
        with self._lock:
            for key in rows:
                if (key not in self._counts
                        and len(self._counts) >= self.max_stacks):
                    m.dropped.inc()
                    continue
                self._counts[key] = self._counts.get(key, 0) + 1
                recorded += 1
            self.samples += 1
            self.stack_samples += recorded
            n_distinct = len(self._counts)
        dt = time.perf_counter() - t0
        self._busy_s += dt
        m.samples.inc()
        m.stacks_seen.inc(recorded)
        m.sample_s.observe(dt)
        m.distinct.set(n_distinct)
        m.threads.set(len(rows))
        if self._started_t is not None:
            elapsed = max(self.clock() - self._started_t, 1e-9)
            m.overhead.set(min(self._busy_s / elapsed, 1.0))
        return recorded

    # -- the sampler thread ------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._started_t = self.clock()
        self._busy_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name="telemetry-pyprof", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            if not ENABLED[0]:
                continue
            try:
                self.sample_once()
            except Exception:  # lint: allow-silent(the profiler must outlive any one bad tick; next tick retries)
                pass

    def stop(self):
        self._stop.set()
        th = self._thread
        if th is not None:
            th.join(timeout=5.0)
        self._thread = None

    def reset(self):
        with self._lock:
            self._counts.clear()
            self.samples = 0
            self.stack_samples = 0
        self._busy_s = 0.0
        if self._started_t is not None:
            self._started_t = self.clock()

    # -- exports -----------------------------------------------------------
    def folded_dict(self, top_n: int | None = None) -> dict[str, int]:
        """``{stack-key: samples}``, optionally only the top-N heaviest
        (what the cluster publisher ships)."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        if top_n is not None:
            items = items[:top_n]
        return dict(items)

    def folded(self, top_n: int | None = None) -> str:
        """Folded flamegraph lines: ``root;frame;...;leaf count``."""
        return "\n".join(f"{k} {v}"
                         for k, v in self.folded_dict(top_n).items())

    def speedscope(self, name: str = "paddle_tpu") -> dict:
        """Speedscope sampled-profile JSON, one profile per root thread."""
        return folded_to_speedscope(self.folded_dict(), name=name,
                                    hz=self.hz)

    def overhead_frac(self) -> float:
        if self._started_t is None:
            return 0.0
        elapsed = max(self.clock() - self._started_t, 1e-9)
        return min(self._busy_s / elapsed, 1.0)

    def stats(self) -> dict:
        with self._lock:
            distinct = len(self._counts)
        return {"hz": self.hz, "samples": self.samples,
                "stack_samples": self.stack_samples,
                "distinct_stacks": distinct,
                "overhead_frac": self.overhead_frac(),
                "running": bool(self._thread and self._thread.is_alive())}


# -- folded-profile algebra (fleet merge) ----------------------------------

def merge_folded(*folded_dicts) -> dict[str, int]:
    """Sum identical stacks across folded dicts — the fleet-wide flame
    view is just the pointwise sum of per-rank tables."""
    out: dict[str, int] = {}
    for d in folded_dicts:
        for k, v in (d or {}).items():
            out[k] = out.get(k, 0) + int(v)
    return dict(sorted(out.items(), key=lambda kv: (-kv[1], kv[0])))


def parse_folded(text: str) -> dict[str, int]:
    """Inverse of :meth:`SamplingProfiler.folded` (tools re-load dumps)."""
    out: dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, n = line.rpartition(" ")
        if stack and n.isdigit():
            out[stack] = out.get(stack, 0) + int(n)
    return out


def folded_to_speedscope(folded: dict[str, int], *, name: str = "profile",
                         hz: float | None = None) -> dict:
    """Speedscope 'sampled' document from a folded table, one profile per
    root frame (= thread name) so each subsystem gets its own view."""
    frames: list[dict] = []
    index: dict[str, int] = {}

    def fidx(fname: str) -> int:
        i = index.get(fname)
        if i is None:
            i = index[fname] = len(frames)
            frames.append({"name": fname})
        return i

    by_root: dict[str, list[tuple[list[int], int]]] = {}
    for stack, count in folded.items():
        parts = stack.split(";")
        by_root.setdefault(parts[0], []).append(
            ([fidx(p) for p in parts], int(count)))

    profiles = []
    for root in sorted(by_root):
        rows = by_root[root]
        total = sum(w for _, w in rows)
        profiles.append({
            "type": "sampled", "name": root, "unit": "none",
            "startValue": 0, "endValue": total,
            "samples": [s for s, _ in rows],
            "weights": [w for _, w in rows],
        })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name,
        "exporter": "paddle_tpu.telemetry.pyprof"
                    + (f" @{hz:g}Hz" if hz else ""),
        "shared": {"frames": frames},
        "profiles": profiles,
        "activeProfileIndex": 0,
    }


# -- process-global install ------------------------------------------------

_INSTALLED: list = [None]


def install(profiler: SamplingProfiler | None = None, *, start: bool = True,
            **kw) -> SamplingProfiler:
    """Install ``profiler`` (or a fresh one built with ``**kw``) as the
    process-global profiler; the cluster publisher ships whatever is
    installed here."""
    old = _INSTALLED[0]
    if old is not None and old is not profiler:
        old.stop()
    if profiler is None:
        profiler = SamplingProfiler(**kw)
    _INSTALLED[0] = profiler
    if start:
        profiler.start()
    return profiler


def installed() -> SamplingProfiler | None:
    return _INSTALLED[0]


def uninstall():
    p = _INSTALLED[0]
    _INSTALLED[0] = None
    if p is not None:
        p.stop()
