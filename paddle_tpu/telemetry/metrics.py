"""Metrics registry: Counter / Gauge / Histogram with label sets.

The design target is the serving hot path — a decode step emits a handful of
observations per *batch*, an engine emits one TTFT observation per
*request* — so the cost model is: one shared-flag check, one dict hit for a
pre-resolved child, one lock'd float add. Callers that care hold on to the
child object (``registry().counter(...).labels(engine="0")``) once and call
``inc``/``set``/``observe`` on it forever after; the get-or-create path is
for setup code only.

Two export formats, both side-effect free snapshots of live state:

- :meth:`MetricsRegistry.prometheus_text` — the Prometheus text exposition
  format (``# HELP`` / ``# TYPE`` / ``name{label="v"} value``, histogram
  ``_bucket{le=...}`` / ``_sum`` / ``_count`` series), scrapeable as-is.
- :meth:`MetricsRegistry.snapshot` — a JSON-able dict written next to bench
  artifacts (``--metrics-out``) and pretty-printed by
  ``tools/metrics_dump.py``.

``telemetry.disable()`` flips the shared :data:`ENABLED` flag: every write
method returns after one list-index check, which is what keeps a
registry-disabled serving run within noise of an instrumented one
(ISSUE 4 acceptance: <= 3% overhead with telemetry *enabled*).
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
from ..analysis import locksan

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "DEFAULT_BUCKETS", "ENABLED",
]

# Shared kill switch (telemetry.disable()/enable() flip it). A mutable
# single-cell list so tracing / flight_recorder can import THE flag object,
# not a copy of its value.
ENABLED = [True]

# Latency-flavored default buckets (seconds): sub-ms decode steps through
# multi-second checkpoint writes all land on a meaningful edge.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _fmt_exemplar(ex) -> str:
    """OpenMetrics exemplar suffix for a _bucket line (empty when the
    bucket has none — the plain-Prometheus exposition is unchanged then):
    `` # {trace_id="abc"} 0.093 1690000000.0``."""
    if not ex:
        return ""
    labels, value, ts = ex
    ls = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels.items())
    return f" # {{{ls}}} {_fmt(value)} {ts:.3f}"


def _escape_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    """Prometheus-friendly number: integers without a trailing .0 noise is
    fine either way, but NaN/inf must spell Prometheus's names."""
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


class _Child:
    """One labeled time series. Holds its own lock; reads are lock-free
    (float/int loads are atomic under the GIL, and consumers tolerate a
    snapshot that is one observation stale)."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = locksan.Lock("metrics.child")


class CounterChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        if not ENABLED[0]:
            return
        if amount < 0:
            raise ValueError(f"counter inc({amount}): counters only go up")
        with self._lock:
            self.value += amount


class GaugeChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def set(self, value: float):
        if not ENABLED[0]:
            return
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0):
        if not ENABLED[0]:
            return
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)


class HistogramChild(_Child):
    __slots__ = ("buckets", "counts", "sum", "count", "exemplars")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        super().__init__()
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        # bucket index -> (labels, value, unix ts): the last observation
        # that landed in the bucket with an exemplar attached — how a p99
        # TTFT bucket links to the exact request trace that caused it
        # (OpenMetrics exemplar semantics; docs/OBSERVABILITY.md)
        self.exemplars: dict[int, tuple] = {}

    def observe(self, value: float, exemplar: dict | None = None):
        if not ENABLED[0]:
            return
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1
            if exemplar:
                self.exemplars[i] = (dict(exemplar), value, time.time())

    @property
    def mean(self) -> float | None:
        return self.sum / self.count if self.count else None

    def cumulative(self) -> list[int]:
        """Per-bucket cumulative counts (the Prometheus ``le`` semantics),
        +Inf last."""
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


_CHILD_TYPES = {"counter": CounterChild, "gauge": GaugeChild,
                "histogram": HistogramChild}


class _Metric:
    """A named metric family: fixed label names, one child per label-value
    tuple. With no label names the family has exactly one (unlabeled) child
    and the write methods proxy to it, so ``registry().counter("x").inc()``
    works without a ``labels()`` hop."""

    kind: str = ""

    def __init__(self, name: str, help: str = "", label_names=(), **opts):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._opts = opts
        self._children: dict[tuple, _Child] = {}
        self._lock = locksan.Lock("metrics.family")
        if not self.label_names:
            self._default = self._make_child()
            self._children[()] = self._default
        else:
            self._default = None

    def _make_child(self):
        return _CHILD_TYPES[self.kind](**self._opts)

    def labels(self, **labelvalues) -> _Child:
        if set(labelvalues) != set(self.label_names):
            raise ValueError(
                f"metric '{self.name}' takes labels {self.label_names}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    def series(self):
        """[(label_dict, child)] snapshot, label-sorted for stable output."""
        items = sorted(self._children.items())
        return [(dict(zip(self.label_names, key)), ch) for key, ch in items]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0):
        self._default.inc(amount)

    @property
    def value(self):
        return self._default.value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float):
        self._default.set(value)

    def inc(self, amount: float = 1.0):
        self._default.inc(amount)

    def dec(self, amount: float = 1.0):
        self._default.dec(amount)

    @property
    def value(self):
        return self._default.value


class Histogram(_Metric):
    kind = "histogram"

    def observe(self, value: float, exemplar: dict | None = None):
        self._default.observe(value, exemplar=exemplar)

    @property
    def sum(self):
        return self._default.sum

    @property
    def count(self):
        return self._default.count

    @property
    def mean(self):
        return self._default.mean


_METRIC_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Name -> metric family. ``counter``/``gauge``/``histogram`` are
    get-or-create: the same (name) always returns the same family, and a
    kind or label-set mismatch on re-registration is a bug, not a merge."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = locksan.Lock("metrics.registry")

    def _get_or_create(self, kind, name, help, label_names, **opts):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind:
                    raise ValueError(
                        f"metric '{name}' already registered as {m.kind}, "
                        f"requested {kind}")
                if tuple(label_names) != m.label_names:
                    raise ValueError(
                        f"metric '{name}' already registered with labels "
                        f"{m.label_names}, requested {tuple(label_names)}")
                return m
            m = _METRIC_TYPES[kind](name, help, label_names, **opts)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labels=()) -> Counter:
        return self._get_or_create("counter", name, help, labels)

    def gauge(self, name, help="", labels=()) -> Gauge:
        return self._get_or_create("gauge", name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create("histogram", name, help, labels,
                                   buckets=buckets)

    def get(self, name) -> _Metric | None:
        return self._metrics.get(name)

    def metrics(self) -> list[_Metric]:
        return [self._metrics[n] for n in sorted(self._metrics)]

    def reset(self):
        """Drop every registered family (tests; live child handles held by
        instrumented code keep working but detach from exposition)."""
        with self._lock:
            self._metrics.clear()

    # -- export ----------------------------------------------------------
    def prometheus_text(self) -> str:
        """The Prometheus text exposition format, one block per family."""
        lines = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labeldict, ch in m.series():
                base = ",".join(f'{k}="{_escape_label(v)}"'
                                for k, v in labeldict.items())
                if m.kind == "histogram":
                    cum = ch.cumulative()
                    exs = dict(ch.exemplars)
                    for i, (edge, c) in enumerate(zip(ch.buckets, cum)):
                        ls = (base + "," if base else "") + f'le="{_fmt(edge)}"'
                        lines.append(f"{m.name}_bucket{{{ls}}} {c}"
                                     + _fmt_exemplar(exs.get(i)))
                    ls = (base + "," if base else "") + 'le="+Inf"'
                    lines.append(f"{m.name}_bucket{{{ls}}} {cum[-1]}"
                                 + _fmt_exemplar(exs.get(len(ch.buckets))))
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}_sum{suffix} {_fmt(ch.sum)}")
                    lines.append(f"{m.name}_count{suffix} {ch.count}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}{suffix} {_fmt(ch.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-able registry dump: {name: {type, help, labels, series}},
        plus a ``__meta__`` entry stamping the capture time so two
        snapshots diff into rates (``tools/metrics_dump.py --diff``).
        Consumers iterating families must skip keys starting with ``__``."""
        out = {"__meta__": {"wall_time": time.time(), "pid": os.getpid()}}
        for m in self.metrics():
            series = []
            for labeldict, ch in m.series():
                if m.kind == "histogram":
                    s = {
                        "labels": labeldict,
                        "buckets": {_fmt(e): c for e, c in
                                    zip(ch.buckets, ch.cumulative())},
                        "sum": ch.sum, "count": ch.count,
                        "mean": ch.mean,
                    }
                    if ch.exemplars:
                        edges = list(ch.buckets) + [float("inf")]
                        s["exemplars"] = {
                            _fmt(edges[i]): {"labels": labels,
                                             "value": value, "ts": ts}
                            for i, (labels, value, ts)
                            in sorted(ch.exemplars.items())}
                    series.append(s)
                else:
                    series.append({"labels": labeldict, "value": ch.value})
            out[m.name] = {"type": m.kind, "help": m.help,
                           "labels": list(m.label_names), "series": series}
        return out

    def snapshot_json(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1, default=str)
        return path


_DEFAULT = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every built-in layer registers into."""
    return _DEFAULT
