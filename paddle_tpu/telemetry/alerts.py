"""SLO alerting: a declarative rule engine over the metrics history.

The :class:`~paddle_tpu.telemetry.history.TimeSeriesStore` answers "what
was goodput doing"; this module answers "should someone be paged about
it". Three rule kinds, all evaluated against history windows (never raw
registry reads — a rule sees exactly what an operator would see on the
dashboard):

- :class:`ThresholdRule` — latest value vs a bound, one alert per
  matching label set (``router_breaker_state >= 2`` pages per replica).
- :class:`AbsenceRule` — a series stopped: missing entirely, rate pinned
  at zero (a counter that stopped advancing — the killed-publisher
  signature), or value flat after having varied. A series that has never
  shown signal cannot be "absent"; presence must be established first.
- :class:`BurnRateRule` — SRE-style multi-window multi-burn-rate SLO
  alerting: with an objective of ``0.99`` the error budget is 1%, the
  burn rate is (windowed error rate) / budget, and a (long, short,
  factor) window pair fires only when BOTH windows exceed the factor —
  the long window proves significance, the short window proves it is
  *still* happening (fast resolve). Defaults follow the SRE workbook:
  fast page at 14.4x over (1h, 5m), slow ticket at 6x over (6h, 30m).
  ``time_scale`` shrinks every window proportionally so chaos tests can
  prove the algebra in seconds instead of hours.

Alert lifecycle is ``pending -> firing -> resolved`` with for-duration
hysteresis on the way up (a condition must hold ``for_s`` before paging)
and ``resolve_s`` hysteresis on the way down (must stay clear before
resolving). Alerts are deduped by (rule, series-key): a firing alert
re-evaluating as active updates in place, it does not re-notify. Every
transition lands in the flight recorder (``alert.firing`` /
``alert.resolved``), moves the ``alerts_firing{rule,severity}`` gauge,
and calls the notifier hook; a firing alert carries an exemplar (e.g.
the trace id behind the window p99) when the rule has an
``exemplar_fn``.

Rules are also constructible from plain dicts (:func:`rule_from_dict` /
:func:`rules_from_json`) so a deployment can ship its rule pack as JSON;
:func:`default_rules` is the built-in pack covering SLO goodput burn,
breaker-open, journal growth, the leak sentinel, and publisher absence.
"""
from __future__ import annotations

import json
import threading
import time

from . import flight_recorder
from .metrics import registry
from ..analysis import locksan

__all__ = [
    "Rule", "ThresholdRule", "AbsenceRule", "BurnRateRule",
    "Alert", "AlertEngine", "default_rules", "rule_from_dict",
    "rules_from_json",
]

SEVERITIES = ("page", "ticket", "info")

_OPS = {
    ">": lambda v, t: v > t, ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t, "<=": lambda v, t: v <= t,
    "==": lambda v, t: v == t, "!=": lambda v, t: v != t,
}

_M = [None]


def _m():
    if _M[0] is None:
        reg = registry()
        class NS:
            firing = reg.gauge(
                "alerts_firing", "alerts currently firing",
                labels=("rule", "severity"))
            evals = reg.counter(
                "alerts_evaluations_total", "rule-evaluation passes")
            transitions = reg.counter(
                "alerts_transitions_total", "alert state transitions",
                labels=("to",))
            notify_errors = reg.counter(
                "alerts_notifier_errors_total",
                "notifier callbacks that raised")
        _M[0] = NS
    return _M[0]


def _scalar(v, field=None):
    """Extract a scalar from a history point value: raw gauges/rates are
    floats; rollups and histogram summaries are dicts ({'mean': ...} /
    {'p99': ...})."""
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, dict):
        for f in ((field,) if field else ()) + ("mean", "last", "rate"):
            x = v.get(f)
            if isinstance(x, (int, float)):
                return float(x)
    return None


def _pick_res(store, window_s: float) -> str:
    """Coarsest-necessary resolution: raw if the raw ring covers the
    window, else 10s, else 1m (mirrors ``TimeSeriesStore.last_window``)."""
    if store.raw_points * store.interval_s >= window_s:
        return "raw"
    return "10s" if store.rollup_points * 10.0 >= window_s else "1m"


def _window_values(store, family, labels, window_s, field=None):
    """[(t, scalar)] across ALL matching series, time-sorted — burn-rate
    rules alert on the fleet aggregate, not per-engine."""
    q = store.query(family, labels=labels, window_s=window_s,
                    res=_pick_res(store, window_s))
    out = []
    for s in q["series"]:
        for p in s["points"]:
            v = _scalar(p["v"], field)
            if v is not None:
                out.append((p["t"], v))
    out.sort(key=lambda tv: tv[0])
    return out


class Rule:
    """Base rule: identity, severity, hysteresis windows, and the
    evaluate contract. ``evaluate_all(store, now) -> [(key, severity,
    active, value, info)]`` — one tuple per alert-able series key."""

    type = "rule"

    def __init__(self, name: str, *, severity: str = "ticket",
                 for_s: float = 0.0, resolve_s: float = 0.0,
                 description: str = "", exemplar_fn=None):
        if severity not in SEVERITIES:
            raise ValueError(f"severity {severity!r} not in {SEVERITIES}")
        self.name = str(name)
        self.severity = severity
        self.for_s = float(for_s)
        self.resolve_s = float(resolve_s)
        self.description = description
        self.exemplar_fn = exemplar_fn

    def evaluate_all(self, store, now: float):
        raise NotImplementedError

    def describe(self) -> dict:
        return {"name": self.name, "type": self.type,
                "severity": self.severity, "for_s": self.for_s,
                "resolve_s": self.resolve_s,
                "description": self.description}


class ThresholdRule(Rule):
    """Latest value ``op`` threshold, one alert per matching label set."""

    type = "threshold"

    def __init__(self, name, family, op, threshold, *, labels=None,
                 field=None, **kw):
        super().__init__(name, **kw)
        if op not in _OPS:
            raise ValueError(f"op {op!r} not in {sorted(_OPS)}")
        self.family = family
        self.op = op
        self.threshold = float(threshold)
        self.labels = dict(labels or {})
        self.field = field

    def evaluate_all(self, store, now):
        q = store.query(self.family, labels=self.labels, res="raw")
        out = []
        for s in q["series"]:
            if not s["points"]:
                continue
            v = _scalar(s["points"][-1]["v"], self.field)
            if v is None:
                continue
            key = ",".join(f"{k}={x}" for k, x in sorted(s["labels"].items()))
            active = _OPS[self.op](v, self.threshold)
            out.append((key, self.severity, active, v,
                        {"threshold": self.threshold, "op": self.op}))
        return out

    def describe(self):
        d = super().describe()
        d.update(family=self.family, op=self.op, threshold=self.threshold,
                 labels=self.labels, field=self.field)
        return d


class AbsenceRule(Rule):
    """A series that was alive went quiet. ``mode``:

    - ``"zero"`` (default): signal = a nonzero scalar; absent when the
      last signal is older than ``absent_for_s`` (a counter-rate pinned
      at 0 — the publisher-stopped signature).
    - ``"flat"``: signal = the value *changing*; for monotone gauges
      like a publish sequence number.
    - ``"missing"``: signal = any fresh point at all; absent when the
      series stops appearing in samples.

    A series that never showed signal is not absent — presence first.
    """

    type = "absence"

    def __init__(self, name, family, *, absent_for_s, labels=None,
                 field=None, mode="zero", **kw):
        kw.setdefault("severity", "page")
        super().__init__(name, **kw)
        if mode not in ("zero", "flat", "missing"):
            raise ValueError(f"mode {mode!r} not in zero/flat/missing")
        self.family = family
        self.absent_for_s = float(absent_for_s)
        self.labels = dict(labels or {})
        self.field = field
        self.mode = mode
        # key -> {"last_signal_t", "last_value", "last_point_t"}
        self._state: dict[str, dict] = {}

    def _signal(self, st: dict, t: float, v: float) -> bool:
        if self.mode == "zero":
            return v != 0.0
        if self.mode == "flat":
            prev = st.get("last_value")
            st["last_value"] = v
            return prev is not None and v != prev
        # missing: any point newer than the last one we saw
        prev_t = st.get("last_point_t")
        st["last_point_t"] = t
        return prev_t is None or t > prev_t

    def evaluate_all(self, store, now):
        q = store.query(self.family, labels=self.labels, res="raw")
        out = []
        for s in q["series"]:
            if not s["points"]:
                continue
            key = ",".join(f"{k}={x}" for k, x in sorted(s["labels"].items()))
            st = self._state.setdefault(key, {})
            # scan every point since the last evaluation, not just the
            # newest: a rate series sampled faster than the evaluator
            # runs alternates signal/zero, and latest-point-only
            # evaluation can phase-lock onto the zeros — reading signal
            # as absence (or absence as signal) indefinitely
            seen = st.get("scanned_t")
            value = None
            for p in s["points"]:
                if seen is not None and p["t"] <= seen:
                    continue
                v = _scalar(p["v"], self.field)
                if v is None:
                    continue
                value = v
                if self._signal(st, p["t"], v):
                    st["last_signal_t"] = p["t"]
            st["scanned_t"] = s["points"][-1]["t"]
            if value is None:
                value = _scalar(s["points"][-1]["v"], self.field)
                if value is None:
                    continue
            last = st.get("last_signal_t")
            quiet = (now - last) if last is not None else None
            active = last is not None and quiet >= self.absent_for_s
            out.append((key, self.severity, active,
                        quiet if quiet is not None else 0.0,
                        {"absent_for_s": self.absent_for_s,
                         "mode": self.mode, "last_value": value}))
        return out

    def describe(self):
        d = super().describe()
        d.update(family=self.family, absent_for_s=self.absent_for_s,
                 labels=self.labels, mode=self.mode, field=self.field)
        return d


# (long_s, short_s, burn factor, severity, window name) — SRE workbook
# defaults: 14.4x over (1h, 5m) pages (2% of a 30d budget in 1h), 6x over
# (6h, 30m) tickets.
DEFAULT_BURN_WINDOWS = (
    (3600.0, 300.0, 14.4, "page", "fast"),
    (21600.0, 1800.0, 6.0, "ticket", "slow"),
)


class BurnRateRule(Rule):
    """Multi-window multi-burn-rate SLO rule over a good-ratio (or
    error-ratio) series. One alert key per window pair; each fires only
    when both its long and short windows burn above the factor."""

    type = "burn_rate"

    def __init__(self, name, family, *, objective=0.99, labels=None,
                 field=None, signal="good_ratio",
                 windows=DEFAULT_BURN_WINDOWS, time_scale=1.0,
                 min_points=2, **kw):
        super().__init__(name, **kw)
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective {objective} must be in (0, 1)")
        if signal not in ("good_ratio", "error_ratio"):
            raise ValueError("signal must be good_ratio or error_ratio")
        self.family = family
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.labels = dict(labels or {})
        self.field = field
        self.signal = signal
        self.time_scale = float(time_scale)
        self.min_points = int(min_points)
        self.windows = []
        for w in windows:
            long_s, short_s, factor, severity = w[0], w[1], w[2], w[3]
            wname = w[4] if len(w) > 4 else f"{factor:g}x"
            self.windows.append((float(long_s) * self.time_scale,
                                 float(short_s) * self.time_scale,
                                 float(factor), severity, wname))

    def _err(self, v: float) -> float:
        e = (1.0 - v) if self.signal == "good_ratio" else v
        return min(max(e, 0.0), 1.0)

    def _burn(self, store, window_s: float):
        vals = _window_values(store, self.family, self.labels, window_s,
                              self.field)
        if len(vals) < self.min_points:
            return None, len(vals)
        errs = [self._err(v) for _, v in vals]
        return (sum(errs) / len(errs)) / self.budget, len(vals)

    def evaluate_all(self, store, now):
        out = []
        for long_s, short_s, factor, severity, wname in self.windows:
            burn_long, n_long = self._burn(store, long_s)
            burn_short, n_short = self._burn(store, short_s)
            active = (burn_long is not None and burn_short is not None
                      and burn_long >= factor and burn_short >= factor)
            value = None
            if burn_long is not None and burn_short is not None:
                value = min(burn_long, burn_short)
            out.append((wname, severity, active, value,
                        {"burn_long": burn_long, "burn_short": burn_short,
                         "factor": factor, "long_s": long_s,
                         "short_s": short_s, "objective": self.objective,
                         "points": [n_long, n_short]}))
        return out

    def describe(self):
        d = super().describe()
        d.update(family=self.family, objective=self.objective,
                 signal=self.signal, labels=self.labels, field=self.field,
                 windows=[list(w) for w in self.windows])
        return d


class Alert:
    """One alert episode for (rule, series key)."""

    __slots__ = ("rule", "key", "severity", "state", "value", "info",
                 "description", "exemplar", "pending_t", "pending_wall",
                 "firing_t", "firing_wall", "clear_t", "resolved_wall",
                 "last_active_t")

    def __init__(self, rule: str, key: str, severity: str,
                 description: str = ""):
        self.rule = rule
        self.key = key
        self.severity = severity
        self.description = description
        self.state = "pending"
        self.value = None
        self.info: dict = {}
        self.exemplar = None
        self.pending_t = self.pending_wall = None
        self.firing_t = self.firing_wall = None
        self.clear_t = None
        self.resolved_wall = None
        self.last_active_t = None

    def doc(self) -> dict:
        return {
            "rule": self.rule, "key": self.key, "severity": self.severity,
            "state": self.state, "value": self.value, "info": self.info,
            "description": self.description, "exemplar": self.exemplar,
            "pending_wall": self.pending_wall,
            "firing_wall": self.firing_wall,
            "resolved_wall": self.resolved_wall,
        }


class AlertEngine:
    """Evaluates rules against a history store on its own thread
    (``telemetry-alerts``), owning the full alert lifecycle."""

    def __init__(self, history, rules=(), *, interval_s: float = 5.0,
                 clock=time.monotonic, wall_clock=time.time,
                 notifier=None, max_history: int = 128):
        self.history = history
        self.rules: list[Rule] = []
        self.interval_s = float(interval_s)
        self.clock = clock
        self.wall_clock = wall_clock
        self.notifier = notifier
        self._alerts: dict[tuple, Alert] = {}
        self._resolved: list[dict] = []
        self.max_history = int(max_history)
        self._gauge_keys: set[tuple] = set()
        self._lock = locksan.Lock("alerts.engine")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.evaluations = 0
        for r in rules:
            self.add_rule(r)

    def add_rule(self, rule: Rule):
        with self._lock:
            if any(r.name == rule.name for r in self.rules):
                raise ValueError(f"duplicate rule name {rule.name!r}")
            self.rules.append(rule)
        return self

    # -- lifecycle ---------------------------------------------------------
    def _notify(self, event: str, alert: Alert):
        flight_recorder.record_event(
            f"alert.{event}", rule=alert.rule, key=alert.key,
            severity=alert.severity, value=alert.value,
            exemplar=alert.exemplar)
        _m().transitions.labels(to=event).inc()
        if self.notifier is not None:
            try:
                self.notifier({"event": event, "alert": alert.doc()})
            except Exception as exc:  # lint: allow-silent(a broken pager integration must not stop evaluation; counted)
                _m().notify_errors.inc()
                flight_recorder.record_event(
                    "alert.notifier_error", rule=alert.rule, key=alert.key,
                    event=event, error=f"{type(exc).__name__}: {exc}")

    def _exemplar(self, rule: Rule):
        if rule.exemplar_fn is None:
            return None
        try:
            return rule.exemplar_fn()
        except Exception:  # lint: allow-silent(exemplars are garnish; the page still goes out without one)
            return None

    def evaluate_once(self) -> list[dict]:
        """One pass over every rule. Returns the transition events
        ([{event, alert}]) this pass produced."""
        now = self.clock()
        wall = self.wall_clock()
        events: list[tuple[str, Alert]] = []
        with self._lock:
            rules = list(self.rules)
        for rule in rules:
            try:
                results = rule.evaluate_all(self.history, now)
            except Exception:  # lint: allow-silent(one bad rule must not stop the pager; next pass retries)
                continue
            for key, severity, active, value, info in results:
                self._step(rule, key, severity, active, value, info,
                           now, wall, events)
        with self._lock:
            self.evaluations += 1
            self._sync_gauge()
        _m().evals.inc()
        for event, alert in events:
            self._notify(event, alert)
        return [{"event": e, "alert": a.doc()} for e, a in events]

    def _step(self, rule: Rule, key, severity, active, value, info,
              now, wall, events):
        akey = (rule.name, key)
        with self._lock:
            alert = self._alerts.get(akey)
            if active:
                if alert is None:
                    alert = Alert(rule.name, key, severity,
                                  rule.description)
                    alert.pending_t, alert.pending_wall = now, wall
                    self._alerts[akey] = alert
                    events.append(("pending", alert))
                alert.value, alert.info = value, dict(info)
                alert.last_active_t = now
                alert.clear_t = None
                if (alert.state == "pending"
                        and now - alert.pending_t >= rule.for_s):
                    alert.state = "firing"
                    alert.firing_t, alert.firing_wall = now, wall
                    alert.exemplar = self._exemplar(rule)
                    events.append(("firing", alert))
            elif alert is not None:
                if alert.state == "pending":
                    # never fired: cancel silently (dedupe — no page,
                    # no resolve noise)
                    del self._alerts[akey]
                elif alert.state == "firing":
                    if alert.clear_t is None:
                        alert.clear_t = now
                    if now - alert.clear_t >= rule.resolve_s:
                        alert.state = "resolved"
                        alert.resolved_wall = wall
                        del self._alerts[akey]
                        self._resolved.append(alert.doc())
                        del self._resolved[:-self.max_history]
                        events.append(("resolved", alert))

    def _sync_gauge(self):
        """alerts_firing{rule,severity}: recomputed each pass; label pairs
        that stopped firing are pinned back to 0 (callers hold the lock)."""
        g = _m().firing
        counts: dict[tuple, int] = {}
        for a in self._alerts.values():
            if a.state == "firing":
                counts[(a.rule, a.severity)] = (
                    counts.get((a.rule, a.severity), 0) + 1)
        self._gauge_keys |= set(counts)
        for rule, severity in self._gauge_keys:
            g.labels(rule=rule, severity=severity).set(
                counts.get((rule, severity), 0))

    # -- the evaluator thread ----------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-alerts", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:  # lint: allow-silent(the evaluator must outlive any one bad pass; next tick retries)
                pass

    def stop(self):
        self._stop.set()
        th = self._thread
        if th is not None:
            th.join(timeout=5.0)
        self._thread = None

    # -- inspection --------------------------------------------------------
    def active(self) -> list[dict]:
        with self._lock:
            return [a.doc() for a in self._alerts.values()]

    def firing(self) -> list[dict]:
        return [a for a in self.active() if a["state"] == "firing"]

    def state(self) -> dict:
        """The ``/v1/alerts`` document."""
        with self._lock:
            alerts = sorted((a.doc() for a in self._alerts.values()),
                            key=lambda d: (d["rule"], d["key"]))
            return {
                "alerts": alerts,
                "firing": sum(1 for a in alerts if a["state"] == "firing"),
                "pending": sum(1 for a in alerts
                               if a["state"] == "pending"),
                "resolved": list(self._resolved),
                "rules": [r.describe() for r in self.rules],
                "evaluations": self.evaluations,
                "interval_s": self.interval_s,
                "running": bool(self._thread and self._thread.is_alive()),
            }


# -- declarative construction ---------------------------------------------

_RULE_TYPES = {"threshold": ThresholdRule, "absence": AbsenceRule,
               "burn_rate": BurnRateRule}


def rule_from_dict(spec: dict) -> Rule:
    """Build a rule from a plain dict: ``{"type": "threshold", "name":
    ..., "family": ..., "op": ">", "threshold": 2, "severity": "page",
    "for_s": 10}`` — the JSON rule grammar (docs/OBSERVABILITY.md)."""
    spec = dict(spec)
    rtype = spec.pop("type", None)
    cls = _RULE_TYPES.get(rtype)
    if cls is None:
        raise ValueError(f"unknown rule type {rtype!r}; "
                         f"one of {sorted(_RULE_TYPES)}")
    name = spec.pop("name")
    family = spec.pop("family")
    if cls is ThresholdRule:
        return cls(name, family, spec.pop("op"), spec.pop("threshold"),
                   **spec)
    if cls is AbsenceRule:
        return cls(name, family, **spec)
    if "windows" in spec:
        spec["windows"] = [tuple(w) for w in spec["windows"]]
    return cls(name, family, **spec)


def rules_from_json(src) -> list[Rule]:
    """A list of rule dicts — given directly, as a JSON string, or as a
    path to a JSON file."""
    if isinstance(src, str):
        s = src.strip()
        if s.startswith("["):
            src = json.loads(s)
        else:
            with open(src) as f:
                src = json.load(f)
    return [rule_from_dict(d) for d in src]


def default_rules(*, objective: float = 0.99, time_scale: float = 1.0,
                  journal_segments_max: float = 64.0,
                  publisher_absent_s: float = 15.0,
                  exemplar_fn=None) -> list[Rule]:
    """The built-in rule pack. ``time_scale`` shrinks burn windows,
    for-durations, and absence windows together so a chaos harness can
    exercise real page timing in seconds."""
    ts = float(time_scale)
    return [
        BurnRateRule(
            "slo-goodput-burn", "slo_goodput_ratio", objective=objective,
            time_scale=ts, for_s=0.0, resolve_s=30.0 * ts,
            exemplar_fn=exemplar_fn,
            description="SLO goodput burning error budget too fast"),
        ThresholdRule(
            "breaker-open", "router_breaker_state", ">=", 2.0,
            severity="ticket", for_s=5.0 * ts, resolve_s=10.0 * ts,
            description="replica circuit breaker open"),
        ThresholdRule(
            "journal-growth", "journal_segments", ">",
            journal_segments_max, severity="ticket", for_s=30.0 * ts,
            resolve_s=30.0 * ts,
            description="journal segment count growing without compaction"),
        ThresholdRule(
            "leak-sentinel", "memory_leak_flags_total", ">", 0.0,
            severity="ticket", for_s=0.0, resolve_s=60.0 * ts,
            description="leak sentinel flagged monotonic growth"),
        AbsenceRule(
            "publisher-absence", "cluster_publish_total",
            absent_for_s=publisher_absent_s * ts, mode="zero",
            severity="page", resolve_s=5.0 * ts,
            description="rank telemetry publisher stopped publishing"),
    ]
