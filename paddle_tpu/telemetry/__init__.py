"""paddle_tpu.telemetry — metrics, tracing, and the flight recorder.

Three observability primitives, one process-global instance of each, shared
by every built-in layer (serving engine, collectives, TCPStore, checkpoint
writer, fault-injection registry) so a single serving process can produce a
Prometheus exposition, a Chrome trace, and a crash postmortem from the same
run (docs/OBSERVABILITY.md has the full tour):

- :mod:`.metrics` — ``Counter`` / ``Gauge`` / ``Histogram`` families with
  label sets in a :func:`registry`; Prometheus text exposition and JSON
  snapshot export. Cheap enough for per-token hot paths.
- :mod:`.tracing` — ``span(name, **attrs)`` context manager; in-process
  span log with trace/span ids, Chrome ``trace.json`` export, and
  forwarding into ``jax.profiler.TraceAnnotation`` while a device trace is
  active so host spans interleave with XLA events.
- :mod:`.flight_recorder` — bounded ring of recent runtime events
  (collective launches, allocator traffic, scheduler decisions, fault
  injections, training bad-steps/resumes/checkpoints), dumped to disk on
  collective/store timeouts, engine stalls, numerical-divergence trips
  (`resilience.HealthGuard`), and uncaught exceptions.

Two cluster-scale layers sit on top (PR 6):

- :mod:`.cluster` — the cross-rank plane: per-rank publishers over the
  TCPStore, fleet aggregation, collective-heartbeat straggler/hang
  diagnosis, multi-rank postmortem bundles, and clock-corrected Chrome
  trace merging (``tools/cluster_status.py`` is the operator CLI).
- :mod:`.slo` — rolling-window TTFT/TPOT/queue percentiles + goodput and
  the admit/shed health signal on ``LLMEngine.stats()["slo"]``.

And the performance layer (PR 9):

- :mod:`.perf` — why did it recompile (``CompileWatcher`` over every jit
  entry point, recompilation-storm detection, ``explain_recompile()``
  signature diffs), where did the memory go (``MemoryMonitor`` per-tag
  live/peak accounting, peak attribution, leak sentinel), and which phase
  got slower (``StepTimeline`` per-phase percentiles + regression
  culprit naming); ``tools/perf_gate.py`` enforces the bench trajectory
  against ``BASELINE.json``.

And the ops plane (PR 19) — the detect half of detect→page→diagnose:

- :mod:`.history` — ``TimeSeriesStore``: a background sampler turns the
  instantaneous registry into bounded raw/10s/1m downsampling rings
  (counters as rates, histograms as quantile summaries); serves the
  gateway ``/v1/history`` + ``/v1/dashboard`` and attaches a last-window
  slice to every flight dump and postmortem bundle.
- :mod:`.alerts` — declarative threshold / absence / multi-window
  SLO-burn-rate rules with a pending→firing→resolved lifecycle,
  ``alerts_firing`` gauge, flight events, and a notifier hook
  (``/v1/alerts``; ``chaos_run --suite alerts`` proves page timing).
- :mod:`.pyprof` — continuous sampling profiler over
  ``sys._current_frames()`` keyed by thread names; folded-flamegraph /
  speedscope exports, self-measured overhead, and per-rank folded
  profiles shipped through :mod:`.cluster` into one fleet-wide flame
  view.

:func:`disable` flips one shared flag that every write path checks first —
the guaranteed-cheap escape hatch for benchmarking the instrumentation
itself (``tools/serving_bench.py --telemetry off``).
"""
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    DEFAULT_BUCKETS,
    registry,
)
from .metrics import ENABLED as _ENABLED
from .tracing import (  # noqa: F401
    Span,
    Tracer,
    device_trace_active,
    mono_to_unix,
    set_device_trace_active,
    span,
    trace_id,
    tracer,
)
from .flight_recorder import (  # noqa: F401
    FlightRecorder,
    dump,
    flight,
    install_excepthook,
    record_event,
)
from . import cluster  # noqa: F401  (cross-rank plane: publisher/monitor/
#                                    aggregator/trace merge — see cluster.py)
from .slo import SLOTracker  # noqa: F401
from . import perf  # noqa: F401  (performance observability: CompileWatcher /
#                                  MemoryMonitor / StepTimeline — see perf.py)
from .perf import (  # noqa: F401
    compile_watcher,
    explain_recompile,
    memory_monitor,
    step_timeline,
)
from . import cost  # noqa: F401  (roofline cost model: jaxpr FLOPs/bytes
#                                  walk + trace-cost registry — see cost.py)
from . import reqtrace  # noqa: F401  (request-scoped trace propagation +
#                                      per-request Chrome merge — reqtrace.py)
from . import history  # noqa: F401  (metrics history: TimeSeriesStore
#                                     downsampling rings — see history.py)
from .history import TimeSeriesStore  # noqa: F401
from . import alerts  # noqa: F401  (SLO burn-rate / threshold / absence
#                                    rule engine — see alerts.py)
from .alerts import AlertEngine, default_rules  # noqa: F401
from . import pyprof  # noqa: F401  (continuous sampling profiler: folded /
#                                    speedscope + fleet merge — pyprof.py)
from .pyprof import SamplingProfiler  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_BUCKETS", "registry", "Span", "Tracer", "span", "tracer",
    "trace_id", "mono_to_unix", "set_device_trace_active",
    "device_trace_active",
    "FlightRecorder", "flight", "record_event", "dump", "install_excepthook",
    "enable", "disable", "enabled", "prometheus_text", "snapshot",
    "cluster", "SLOTracker", "perf", "compile_watcher", "memory_monitor",
    "step_timeline", "explain_recompile", "cost", "reqtrace",
    "history", "TimeSeriesStore", "alerts", "AlertEngine", "default_rules",
    "pyprof", "SamplingProfiler",
]


def disable():
    """Turn every telemetry write path into a single flag check (metrics,
    spans, flight events all stop recording; reads keep working)."""
    _ENABLED[0] = False


def enable():
    _ENABLED[0] = True


def enabled() -> bool:
    return _ENABLED[0]


def prometheus_text() -> str:
    """Exposition of the global registry (shorthand)."""
    return registry().prometheus_text()


def snapshot() -> dict:
    """JSON snapshot of the global registry (shorthand)."""
    return registry().snapshot()
