"""Metrics history: a bounded in-process time-series store over the registry.

The registry (:mod:`paddle_tpu.telemetry.metrics`) is *instantaneous* — a
scrape sees the current counter value and nothing else. Operating a fleet
needs the other axis: "what was goodput doing for the last five minutes",
"did journal segments grow monotonically before the crash", "what did the
decode p99 look like while the breaker was open". :class:`TimeSeriesStore`
is that axis, kept deliberately small:

- A background sampler (``telemetry-history-sampler``) snapshots the
  registry every ``interval_s`` into per-series **downsampling rings**:
  a raw ring (one point per tick) plus 10s and 1m rollup rings, each
  bounded, so total memory is fixed regardless of uptime.
- **Counters are stored as rates** (delta / dt against the previous
  cumulative value — a restart shows as a rate dip, not a cliff of
  -1e9), gauges as values, and **histograms as quantile summaries**
  ({rate, mean, p50, p90, p99} derived from bucket deltas between
  consecutive snapshots — the same interpolation ``tools/metrics_dump.py
  --diff`` prints).
- Rollups are pure functions of the sample sequence: the same snapshots
  fed at the same timestamps produce byte-identical rollup rings
  (clocks are injectable), which is what makes the ring math testable.
- :meth:`TimeSeriesStore.query` serves the gateway ``/v1/history``
  endpoint and the alert engine; :meth:`TimeSeriesStore.last_window` is
  the compact slice attached to every flight-recorder dump and
  postmortem bundle, so an autopsy answers "what was happening the five
  minutes *before* it died" instead of only "what was true at death".
- :meth:`add_source` lets non-registry collectors (e.g. a chaos harness
  sampling rank publish sequence numbers off the TCPStore) inject extra
  families into the same rings; absence alerting keys off those.

Sampling overhead is self-measured and exported (``history_overhead_frac``:
sampler busy-time over elapsed time) so the cost of observing is itself
observable — and gated by ``tools/perf_gate.py``
(``history_sampler_overhead_frac``).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

from . import flight_recorder
from .metrics import ENABLED, registry
from ..analysis import locksan

__all__ = [
    "TimeSeriesStore", "install", "installed", "uninstall",
    "RESOLUTIONS", "HISTORY_FLIGHT_PROVIDER",
]

# Resolution tiers: name -> rollup period in seconds (None = raw ticks).
RESOLUTIONS = (("raw", None), ("10s", 10.0), ("1m", 60.0))
_PERIODS = dict(RESOLUTIONS)

# Histogram-summary fields aggregated by max in rollups (tail quantiles
# should not be averaged away); everything else numeric rolls up by mean.
_MAX_FIELDS = ("p50", "p90", "p99")

HISTORY_FLIGHT_PROVIDER = "history"

_M = [None]


def _m():
    """Self-metrics, registered lazily into the global registry."""
    if _M[0] is None:
        reg = registry()
        class NS:
            samples = reg.counter(
                "history_samples_total", "registry snapshots ingested")
            points = reg.counter(
                "history_points_total", "raw points appended across series")
            series = reg.gauge(
                "history_series", "live time series tracked")
            dropped = reg.counter(
                "history_series_dropped_total",
                "new series rejected by the max_series cap")
            sample_s = reg.histogram(
                "history_sample_seconds", "wall cost of one sample tick",
                buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                         0.1, 0.25))
            overhead = reg.gauge(
                "history_overhead_frac",
                "sampler busy-time fraction since start (self-measured)")
            source_errors = reg.counter(
                "history_source_errors_total",
                "external source callbacks that raised", labels=("source",))
        _M[0] = NS
    return _M[0]


def _quantile(edges, cums, count, q):
    """Linear-interpolated quantile from cumulative bucket counts (the
    ``metrics_dump`` convention). ``edges`` excludes +Inf; the overflow
    bucket clamps to the top finite edge."""
    if count <= 0:
        return None
    target = q * count
    prev_cum, prev_edge = 0, 0.0
    for edge, cum in zip(edges, cums):
        if cum >= target:
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span else 1.0
            return prev_edge + (edge - prev_edge) * frac
        prev_cum, prev_edge = cum, edge
    return edges[-1] if edges else None


def _rollup(points):
    """Aggregate a list of point values into one rollup point. Scalars
    roll up to {n, mean, min, max, last}; dict points (histogram
    summaries) roll up field-wise — mean for rates/means, max for tail
    quantiles — skipping None fields. Pure function: same points in the
    same order -> same output."""
    if not points:
        return None
    if isinstance(points[0], dict):
        out = {"n": len(points)}
        fields = []
        for p in points:
            for f in p:
                if f not in fields:
                    fields.append(f)
        for f in fields:
            vals = [p[f] for p in points
                    if isinstance(p.get(f), (int, float))]
            if not vals:
                out[f] = None
            elif f in _MAX_FIELDS:
                out[f] = max(vals)
            else:
                out[f] = sum(vals) / len(vals)
        return out
    vals = [float(p) for p in points]
    return {"n": len(vals), "mean": sum(vals) / len(vals),
            "min": min(vals), "max": max(vals), "last": vals[-1]}


class _RollupRing:
    """One rollup tier: buckets of ``period`` seconds, finalized when a
    sample lands in a later bucket, kept in a bounded deque."""

    __slots__ = ("period", "ring", "cur_bucket", "cur_wall", "cur_points")

    def __init__(self, period: float, maxlen: int):
        self.period = float(period)
        self.ring: deque = deque(maxlen=maxlen)
        self.cur_bucket: float | None = None
        self.cur_wall = 0.0
        self.cur_points: list = []

    def add(self, t: float, wall: float, point):
        bucket = (t // self.period) * self.period
        if self.cur_bucket is None:
            self.cur_bucket = bucket
        elif bucket != self.cur_bucket:
            agg = _rollup(self.cur_points)
            if agg is not None:
                self.ring.append((self.cur_bucket, self.cur_wall, agg))
            self.cur_bucket, self.cur_points = bucket, []
        self.cur_wall = wall
        self.cur_points.append(point)

    def points(self):
        """Finalized buckets plus the live partial bucket (aggregated on
        the fly — still deterministic given the same sample sequence)."""
        out = list(self.ring)
        if self.cur_points:
            agg = _rollup(self.cur_points)
            if agg is not None:
                out.append((self.cur_bucket, self.cur_wall, agg))
        return out


class _Series:
    __slots__ = ("family", "kind", "labels", "raw", "rollups",
                 "prev_t", "prev_counter", "prev_hist")

    def __init__(self, family, kind, labels, raw_points, rollup_points):
        self.family = family
        self.kind = kind
        self.labels = dict(labels)
        self.raw: deque = deque(maxlen=raw_points)
        self.rollups = {name: _RollupRing(period, rollup_points)
                        for name, period in RESOLUTIONS if period}
        self.prev_t: float | None = None
        self.prev_counter: float | None = None
        # (count, sum, cumulative-bucket list) at the previous sample
        self.prev_hist: tuple | None = None

    def add(self, t: float, wall: float, point):
        self.raw.append((t, wall, point))
        for ring in self.rollups.values():
            ring.add(t, wall, point)

    def points(self, res: str):
        if res == "raw":
            return list(self.raw)
        return self.rollups[res].points()


class TimeSeriesStore:
    """Bounded metrics history over a :class:`MetricsRegistry`.

    ``clock`` must be monotonic (durations and bucket edges come from it);
    ``wall_clock`` only stamps points for display. Both are injectable so
    ring math is deterministic under test.
    """

    def __init__(self, reg=None, *, interval_s: float = 1.0,
                 raw_points: int = 600, rollup_points: int = 360,
                 max_series: int = 4096, flight_window_s: float = 300.0,
                 clock=time.monotonic, wall_clock=time.time):
        self.reg = reg if reg is not None else registry()
        self.interval_s = float(interval_s)
        self.raw_points = int(raw_points)
        self.rollup_points = int(rollup_points)
        self.max_series = int(max_series)
        self.flight_window_s = float(flight_window_s)
        self.clock = clock
        self.wall_clock = wall_clock
        self._series: dict[tuple, _Series] = {}
        self._sources: dict[str, object] = {}
        self._lock = locksan.Lock("history.store")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._started_t: float | None = None
        self._busy_s = 0.0
        self.samples = 0

    # -- sources ----------------------------------------------------------
    def add_source(self, name: str, fn):
        """Register an external collector: ``fn() -> {family: {"type":
        kind, "series": [{"labels": {...}, "value": v}, ...]}}`` merged
        into every sample tick (counters from sources get the same
        rate treatment as registry counters)."""
        with self._lock:
            self._sources[str(name)] = fn

    def remove_source(self, name: str):
        with self._lock:
            self._sources.pop(str(name), None)

    # -- ingestion --------------------------------------------------------
    def sample_once(self) -> int:
        """Snapshot the registry (+ sources) into the rings once.
        Returns the number of points appended. Never raises on source
        failures (counted per-source instead)."""
        t0 = time.perf_counter()
        t, wall = self.clock(), self.wall_clock()
        doc = self.reg.snapshot()
        with self._lock:
            sources = dict(self._sources)
        for name, fn in sources.items():
            try:
                extra = fn() or {}
                for fam, block in extra.items():
                    have = doc.get(fam)
                    if have is None:
                        doc[fam] = block
                    else:
                        # the local registry may already expose this
                        # family (e.g. cluster_publish_total is registered
                        # in every process) — source series carry their
                        # own label sets, so merge rather than discard
                        have = dict(have)
                        have["series"] = (list(have.get("series", ()))
                                          + list(block.get("series", ())))
                        doc[fam] = have
            except Exception:  # lint: allow-silent(a broken source must not stop the sampler; counted per-source)
                _m().source_errors.labels(source=name).inc()
        n = self._ingest(doc, t, wall)
        dt = time.perf_counter() - t0
        self._busy_s += dt
        m = _m()
        m.samples.inc()
        m.sample_s.observe(dt)
        if self._started_t is not None:
            elapsed = max(self.clock() - self._started_t, 1e-9)
            m.overhead.set(min(self._busy_s / elapsed, 1.0))
        return n

    def _ingest(self, doc: dict, t: float, wall: float) -> int:
        """Feed one snapshot dict at (t, wall). Split out from
        :meth:`sample_once` so replay/tests can feed recorded snapshot
        sequences and assert identical rollups."""
        added = 0
        with self._lock:
            for fam, block in doc.items():
                if fam.startswith("__") or not isinstance(block, dict):
                    continue
                kind = block.get("type")
                if kind not in ("counter", "gauge", "histogram"):
                    continue
                for s in block.get("series", ()):
                    labels = s.get("labels") or {}
                    key = (fam, tuple(sorted(labels.items())))
                    ser = self._series.get(key)
                    if ser is None:
                        if len(self._series) >= self.max_series:
                            _m().dropped.inc()
                            continue
                        ser = _Series(fam, kind, labels,
                                      self.raw_points, self.rollup_points)
                        self._series[key] = ser
                    point = self._to_point(ser, s, t)
                    if point is not None:
                        ser.add(t, wall, point)
                        added += 1
            _m().series.set(len(self._series))
        self.samples += 1
        if added:
            _m().points.inc(added)
        return added

    def _to_point(self, ser: _Series, s: dict, t: float):
        """Convert one snapshot series entry into a point: gauge value,
        counter rate, or histogram quantile summary. Returns None for the
        first counter/histogram sample (no interval to rate over yet)."""
        if ser.kind == "gauge":
            return float(s.get("value", 0.0))
        if ser.kind == "counter":
            v = float(s.get("value", 0.0))
            prev_t, prev_v = ser.prev_t, ser.prev_counter
            ser.prev_t, ser.prev_counter = t, v
            if prev_t is None or t <= prev_t:
                return None
            delta = v - prev_v if v >= prev_v else v  # reset -> restart
            return max(delta, 0.0) / (t - prev_t)
        # histogram
        buckets = s.get("buckets") or {}
        edges = sorted(float(e) for e in buckets)
        cums = [int(buckets[k]) for k in
                sorted(buckets, key=lambda k: float(k))]
        count = int(s.get("count", 0))
        total = float(s.get("sum", 0.0))
        prev = ser.prev_hist
        prev_t = ser.prev_t
        ser.prev_hist = (count, total, cums)
        ser.prev_t = t
        if prev is None or prev_t is None or t <= prev_t:
            return None
        pc, ps, pcums = prev
        if count < pc or len(pcums) != len(cums):  # reset/reshape
            pc, ps, pcums = 0, 0.0, [0] * len(cums)
        dc = count - pc
        dcums = [c - p for c, p in zip(cums, pcums)]
        point = {"rate": dc / (t - prev_t)}
        if dc > 0:
            point["mean"] = (total - ps) / dc
            for q, f in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                point[f] = _quantile(edges, dcums, dc, q)
        else:
            point.update(mean=None, p50=None, p90=None, p99=None)
        return point

    # -- the sampler thread -----------------------------------------------
    def start(self):
        """Start the background sampler (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._started_t = self.clock()
        self._busy_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name="telemetry-history-sampler", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if not ENABLED[0]:
                continue
            try:
                self.sample_once()
            except Exception:  # lint: allow-silent(the sampler must outlive any one bad snapshot; next tick retries)
                pass

    def stop(self):
        self._stop.set()
        th = self._thread
        if th is not None:
            th.join(timeout=5.0)
        self._thread = None

    # -- queries ----------------------------------------------------------
    def families(self) -> list[dict]:
        with self._lock:
            fams: dict[str, dict] = {}
            for (fam, _), ser in sorted(self._series.items()):
                f = fams.setdefault(fam, {"family": fam, "type": ser.kind,
                                          "series": 0})
                f["series"] += 1
            return list(fams.values())

    def query(self, family: str, labels: dict | None = None,
              window_s: float | None = None, res: str = "raw") -> dict:
        """Points for one family: ``{"family", "type", "res", "series":
        [{"labels", "points": [{"t", "wall", "v"}, ...]}]}``. ``labels``
        is a subset filter; ``window_s`` trims to the trailing window of
        the (monotonic) sample clock."""
        if res not in _PERIODS:
            raise ValueError(f"unknown resolution {res!r}; "
                             f"one of {sorted(_PERIODS)}")
        now = self.clock()
        labels = labels or {}
        out = {"family": family, "type": None, "res": res, "series": []}
        with self._lock:
            for (fam, _), ser in sorted(self._series.items()):
                if fam != family:
                    continue
                if any(str(ser.labels.get(k)) != str(v)
                       for k, v in labels.items()):
                    continue
                out["type"] = ser.kind
                pts = ser.points(res)
                if window_s is not None:
                    cutoff = now - float(window_s)
                    pts = [p for p in pts if p[0] >= cutoff]
                out["series"].append({
                    "labels": dict(ser.labels),
                    "points": [{"t": p[0], "wall": p[1], "v": p[2]}
                               for p in pts],
                })
        return out

    def last_window(self, window_s: float | None = None,
                    max_points_per_series: int = 120) -> dict:
        """The compact slice a flight dump / postmortem bundle carries:
        every family, trailing ``window_s``, at the coarsest resolution
        that still covers the window, tail-capped per series."""
        window_s = self.flight_window_s if window_s is None else window_s
        res = "raw"
        if self.raw_points * self.interval_s < window_s:
            res = "10s" if self.rollup_points * 10.0 >= window_s else "1m"
        now = self.clock()
        cutoff = now - float(window_s)
        fams: dict[str, dict] = {}
        with self._lock:
            n_series = len(self._series)
            for (fam, _), ser in sorted(self._series.items()):
                pts = [p for p in ser.points(res) if p[0] >= cutoff]
                pts = pts[-max_points_per_series:]
                if not pts:
                    continue
                block = fams.setdefault(fam, {"type": ser.kind,
                                              "series": []})
                block["series"].append({
                    "labels": dict(ser.labels),
                    "points": [[round(p[0], 4), round(p[1], 3), p[2]]
                               for p in pts],
                })
        return {
            "window_s": window_s, "res": res,
            "captured_wall": self.wall_clock(), "captured_t": now,
            "interval_s": self.interval_s, "n_series": n_series,
            "samples": self.samples,
            "families": fams,
        }

    # -- export / import --------------------------------------------------
    def to_doc(self) -> dict:
        """Full JSON-able dump of every ring (raw + finalized rollups)."""
        with self._lock:
            series = []
            for (fam, _), ser in sorted(self._series.items()):
                series.append({
                    "family": fam, "type": ser.kind,
                    "labels": dict(ser.labels),
                    "raw": [list(p) for p in ser.raw],
                    "rollups": {name: [list(p) for p in ring.points()]
                                for name, ring in ser.rollups.items()},
                })
        return {
            "version": 1,
            "config": {"interval_s": self.interval_s,
                       "raw_points": self.raw_points,
                       "rollup_points": self.rollup_points},
            "samples": self.samples,
            "series": series,
        }

    def export_json(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_doc(), f, indent=1, default=str)
        return path

    @classmethod
    def from_doc(cls, doc: dict, **kw) -> "TimeSeriesStore":
        """Rebuild a (query-only) store from :meth:`to_doc` output —
        postmortem tooling loads a bundle's history back and queries it
        like a live one. Rate state is not restored; a revived store fed
        new samples treats the first tick as a fresh baseline."""
        cfg = doc.get("config", {})
        store = cls(reg=kw.pop("reg", None),
                    interval_s=cfg.get("interval_s", 1.0),
                    raw_points=cfg.get("raw_points", 600),
                    rollup_points=cfg.get("rollup_points", 360), **kw)
        store.samples = int(doc.get("samples", 0))
        for s in doc.get("series", ()):
            key = (s["family"], tuple(sorted((s.get("labels") or {}).items())))
            ser = _Series(s["family"], s.get("type", "gauge"),
                          s.get("labels") or {},
                          store.raw_points, store.rollup_points)
            for p in s.get("raw", ()):
                ser.raw.append((p[0], p[1], p[2]))
            for name, pts in (s.get("rollups") or {}).items():
                ring = ser.rollups.get(name)
                if ring is None:
                    continue
                for p in pts:
                    ring.ring.append((p[0], p[1], p[2]))
            store._series[key] = ser
        return store

    @classmethod
    def import_json(cls, path: str, **kw) -> "TimeSeriesStore":
        with open(path) as f:
            return cls.from_doc(json.load(f), **kw)

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            n = len(self._series)
        overhead = 0.0
        if self._started_t is not None:
            elapsed = max(self.clock() - self._started_t, 1e-9)
            overhead = min(self._busy_s / elapsed, 1.0)
        return {"series": n, "samples": self.samples,
                "interval_s": self.interval_s,
                "running": bool(self._thread and self._thread.is_alive()),
                "overhead_frac": overhead,
                "sources": sorted(self._sources)}


_INSTALLED: list = [None]


def install(store: TimeSeriesStore | None = None, *, start: bool = True,
            **kw) -> TimeSeriesStore:
    """Install ``store`` (or a fresh one built with ``**kw``) as the
    process-global history: starts its sampler and registers the
    flight-recorder context provider so every dump carries the last
    window. Idempotent-ish: installing over an existing store stops the
    old sampler first."""
    old = _INSTALLED[0]
    if old is not None and old is not store:
        old.stop()
    if store is None:
        store = TimeSeriesStore(**kw)
    _INSTALLED[0] = store
    flight_recorder.register_context_provider(
        HISTORY_FLIGHT_PROVIDER, lambda: store.last_window())
    if start:
        store.start()
    return store


def installed() -> TimeSeriesStore | None:
    return _INSTALLED[0]


def uninstall():
    store = _INSTALLED[0]
    _INSTALLED[0] = None
    flight_recorder.unregister_context_provider(HISTORY_FLIGHT_PROVIDER)
    if store is not None:
        store.stop()


# Re-exported for metrics_dump-style consumers that want the same
# interpolation on delta buckets.
quantile_from_buckets = _quantile
