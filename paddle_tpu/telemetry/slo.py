"""Serving SLO tracker: rolling-window latency percentiles + goodput.

The engine's Prometheus histograms (`serving_ttft_seconds`, ...) are
cumulative-forever — right for a scraper computing windowed rates, wrong
for an in-process router/load-shedder that needs "p99 TTFT over the last
minute, now". :class:`SLOTracker` keeps the raw per-request observations
the engine already produces (the same values it feeds the histograms) in
a time-bounded window and derives:

- **percentiles** — p50/p95/p99 of TTFT, TPOT, and queue time over the
  window (nearest-rank on the sorted samples);
- **goodput** — the fraction of generated tokens attributable to requests
  that met their SLO (``ttft <= ttft_slo_s`` and ``tpot <= tpot_slo_s``;
  failed/cancelled requests always count against it), per the goodput
  framing of serving papers: tokens you'd have to re-serve don't count;
- **a shed/admit health signal** — ``healthy`` is False once the window
  p99s exceed the SLO (with at least ``min_samples`` requests observed),
  which is exactly what a fleet gateway polls before routing more load at
  a replica. Surfaced on ``LLMEngine.stats()["slo"]``.

Every :meth:`summary` also publishes ``slo_*`` gauges into the global
registry (labeled per engine), so the same numbers ride the per-rank
snapshots into the cluster aggregation plane (`telemetry.cluster`).

With no SLOs configured the tracker still reports percentiles and treats
every finished request as within SLO — goodput then measures only
failure/cancellation waste. Writes respect ``telemetry.disable()``.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from .metrics import ENABLED, registry
from ..analysis import locksan

__all__ = ["SLOTracker"]


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile on an already-sorted sample list."""
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _pct_exemplar(sorted_pairs: list[tuple], q: float) -> str | None:
    """The trace id of the nearest-rank sample at quantile ``q`` (the
    request that *is* the window p99, not a neighbor)."""
    if not sorted_pairs:
        return None
    idx = max(0, min(len(sorted_pairs) - 1,
                     int(round(q * (len(sorted_pairs) - 1)))))
    return sorted_pairs[idx][1]


def _slo_metrics(engine_label: str):
    reg = registry()
    ls = ("engine",)

    def G(name, help):
        return reg.gauge(name, help, ls).labels(engine=engine_label)

    return {
        "ttft_p99": G("slo_ttft_p99_seconds",
                      "rolling-window p99 time-to-first-token"),
        "tpot_p99": G("slo_tpot_p99_seconds",
                      "rolling-window p99 per-output-token time"),
        "queue_p99": G("slo_queue_time_p99_seconds",
                       "rolling-window p99 queue time"),
        "goodput": G("slo_goodput_ratio",
                     "tokens within SLO / tokens generated (window)"),
        "req_goodput": G("slo_request_goodput_ratio",
                         "requests within SLO / requests finished (window)"),
        "healthy": G("slo_healthy",
                     "1 = window p99s within SLO (admit), 0 = shed"),
        "window_requests": G("slo_window_requests",
                             "requests in the rolling SLO window"),
    }


class SLOTracker:
    """Rolling window of per-request serving observations.

    ttft_slo_s / tpot_slo_s: the SLO (None = not enforced; the signal
    stays healthy and goodput only penalizes failures).
    window_s:    observation retention horizon.
    max_samples: hard bound on the window (oldest evicted) so a burst
                 cannot grow memory without bound.
    min_samples: don't declare a replica unhealthy off fewer requests.
    """

    def __init__(self, *, ttft_slo_s: float | None = None,
                 tpot_slo_s: float | None = None, window_s: float = 120.0,
                 max_samples: int = 8192, min_samples: int = 5,
                 engine_label: str = "0", clock=time.monotonic):
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self._clock = clock
        # (t, ttft, tpot, queue_time, tokens, ok, trace_id) — ok=None marks
        # a failed/cancelled request (no latency sample, counts as
        # violation); trace_id is the request-trace exemplar the summary's
        # p99s link back to (telemetry.reqtrace)
        self._win: deque[tuple] = deque(maxlen=int(max_samples))
        self._lock = locksan.Lock("slo.tracker")
        # external pressure overlay (e.g. the scheduler's KV-pool
        # watermark latch): while set, the shed verdict is forced
        # regardless of latency percentiles or min_samples — a pool out
        # of blocks sheds even if the window looks healthy
        self._pressure = False
        self._pressure_reason: str | None = None
        self._m = _slo_metrics(engine_label)
        if ENABLED[0]:
            # vacuous-truth defaults: an idle engine admits (healthy=1),
            # it is not "shedding with goodput 0"
            self._m["healthy"].set(1.0)
            self._m["goodput"].set(1.0)
            self._m["req_goodput"].set(1.0)

    # -- recording -------------------------------------------------------
    def record_finished(self, *, ttft: float | None, tpot: float | None,
                        queue_time: float | None, tokens: int,
                        trace_id: str | None = None):
        if not ENABLED[0]:
            return
        ok = True
        if self.ttft_slo_s is not None and ttft is not None:
            ok = ok and ttft <= self.ttft_slo_s
        if self.tpot_slo_s is not None and tpot is not None:
            ok = ok and tpot <= self.tpot_slo_s
        with self._lock:
            self._win.append((self._clock(), ttft, tpot, queue_time,
                              int(tokens), ok, trace_id))

    def set_pressure(self, active: bool, reason: str | None = None):
        """Arm/clear the external pressure overlay. The caller that owns
        a non-latency shed signal (KV-pool watermarks, an operator
        switch) reports it here and the ``shed``/``healthy`` verdict the
        router polls reflects it immediately."""
        self._pressure = bool(active)
        self._pressure_reason = reason if active else None

    def record_failed(self, tokens: int = 0, trace_id: str | None = None):
        """A failed or cancelled request: its tokens (already streamed to
        a client that won't use them) count against goodput."""
        if not ENABLED[0]:
            return
        with self._lock:
            self._win.append((self._clock(), None, None, None,
                              int(tokens), None, trace_id))

    # -- reading ---------------------------------------------------------
    def _window(self):
        cutoff = self._clock() - self.window_s
        with self._lock:
            while self._win and self._win[0][0] < cutoff:
                self._win.popleft()
            return list(self._win)

    def summary(self) -> dict:
        """The window digested: percentiles, goodput, and the admit/shed
        verdict. Also refreshes the ``slo_*`` gauges."""
        win = self._window()
        # a window with zero observations carries no information: mark it
        # `empty` and report goodput as None rather than echoing a vacuous
        # 1.0 that reads like "the last populated window was healthy"
        empty = not win
        # key on the value alone: trace ids may be None and must not be
        # drawn into tie-break comparisons
        ttft_pairs = sorted(((v[1], v[6]) for v in win if v[1] is not None),
                            key=lambda p: p[0])
        tpot_pairs = sorted(((v[2], v[6]) for v in win if v[2] is not None),
                            key=lambda p: p[0])
        ttfts = [p[0] for p in ttft_pairs]
        tpots = [p[0] for p in tpot_pairs]
        queues = sorted(v[3] for v in win if v[3] is not None)
        total_tokens = sum(v[4] for v in win)
        good_tokens = sum(v[4] for v in win if v[5] is True)
        finished = [v for v in win if v[5] is not None]
        good_requests = sum(1 for v in finished if v[5])

        def pcts(vals):
            return {"p50": _percentile(vals, 0.50),
                    "p95": _percentile(vals, 0.95),
                    "p99": _percentile(vals, 0.99)}

        ttft_p, tpot_p, queue_p = pcts(ttfts), pcts(tpots), pcts(queues)
        healthy = True
        if len(win) >= self.min_samples:
            if (self.ttft_slo_s is not None and ttft_p["p99"] is not None
                    and ttft_p["p99"] > self.ttft_slo_s):
                healthy = False
            if (self.tpot_slo_s is not None and tpot_p["p99"] is not None
                    and tpot_p["p99"] > self.tpot_slo_s):
                healthy = False
        shed_reason = None if healthy else "latency"
        if self._pressure:       # authoritative: not gated on min_samples
            healthy = False
            shed_reason = self._pressure_reason or "pressure"
        out = {
            "window_s": self.window_s,
            "window_requests": len(win),
            "empty": empty,
            "ttft_slo_s": self.ttft_slo_s,
            "tpot_slo_s": self.tpot_slo_s,
            "ttft": ttft_p,
            "tpot": tpot_p,
            "queue_time": queue_p,
            "total_tokens": total_tokens,
            "goodput_tokens": good_tokens,
            "goodput_ratio": (None if empty else
                              (good_tokens / total_tokens
                               if total_tokens else 1.0)),
            "request_goodput_ratio": (None if empty
                                      else good_requests / len(win)),
            "healthy": healthy,
            "shed": not healthy,
            "shed_reason": shed_reason,
            # trace-id exemplars: the exact request behind each window p99
            # (GET /v1/traces/<id> on the gateway renders its timeline)
            "exemplars": {
                "ttft_p99": _pct_exemplar(ttft_pairs, 0.99),
                "tpot_p99": _pct_exemplar(tpot_pairs, 0.99),
            },
        }
        if ENABLED[0]:
            m = self._m
            m["ttft_p99"].set(ttft_p["p99"] or 0.0)
            m["tpot_p99"].set(tpot_p["p99"] or 0.0)
            m["queue_p99"].set(queue_p["p99"] or 0.0)
            # empty window: gauges fall back to the idle-engine defaults
            # (goodput 1.0 = nothing to re-serve) rather than None
            m["goodput"].set(1.0 if empty else out["goodput_ratio"])
            m["req_goodput"].set(
                1.0 if empty else out["request_goodput_ratio"])
            m["healthy"].set(1.0 if healthy else 0.0)
            m["window_requests"].set(len(win))
        return out

    def healthy(self) -> bool:
        """The boolean a router/load-shedder polls (admit=True)."""
        return self.summary()["healthy"]
