"""Structured host-side span tracing.

``span(name, **attrs)`` is a context manager producing an in-process event
log with trace/span/parent ids (thread-local nesting), exportable as a
Chrome ``trace.json`` (``chrome://tracing`` / Perfetto load it directly).
When a device trace is active — the ``paddle_tpu.profiler.Profiler`` flips
:func:`set_device_trace_active` around ``jax.profiler.start_trace`` /
``stop_trace`` — every span additionally enters a
``jax.profiler.TraceAnnotation``, so host-side request/engine spans
interleave with XLA's own device events in the exported xprof trace.

Spans that do not correspond to a live ``with`` block (e.g. a request's
queued -> prefill -> decode lifecycle, reconstructed at finish time from its
timestamps) are emitted directly with :meth:`Tracer.emit`, optionally onto a
virtual thread (``tid=``/``tid_name=``) so each request renders as its own
nested timeline row.

All timestamps are ``time.monotonic()`` seconds — the same clock the
serving scheduler stamps requests with — converted to microseconds relative
to a module-load epoch at export time.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time

from .metrics import ENABLED
from ..analysis import locksan

__all__ = ["Span", "Tracer", "tracer", "span", "trace_id", "epoch_unix",
           "mono_to_unix", "set_device_trace_active", "device_trace_active"]

_EPOCH = time.monotonic()
_TRACE_ID = f"{os.getpid():x}-{os.urandom(4).hex()}"
_SPAN_IDS = itertools.count(1)
_DEVICE_TRACE = [False]
_TLS = threading.local()


def trace_id() -> str:
    """This process's trace id (stamped on every exported span)."""
    return _TRACE_ID


def epoch_unix() -> float:
    """Wall-clock time corresponding to exported trace ``ts=0`` (the
    module-load monotonic epoch). Cross-rank trace merge
    (:func:`telemetry.cluster.merge_traces`) uses this plus a per-rank
    clock offset to place every rank's events on one shared timeline."""
    # lint: allow-wallclock(this IS the wall<->mono offset computation)
    return time.time() - (time.monotonic() - _EPOCH)


def mono_to_unix(t_mono: float) -> float:
    """Wall-clock time of a ``time.monotonic()`` stamp on THIS process's
    clock — how request-scoped spans are serialized across the replica pipe
    (``telemetry.reqtrace``): the worker stamps spans in unix time so the
    router can place hops from different processes on one timeline."""
    return epoch_unix() + (float(t_mono) - _EPOCH)


def set_device_trace_active(active: bool):
    """Profiler hook: while True, spans forward to
    jax.profiler.TraceAnnotation so they land in the device trace too."""
    _DEVICE_TRACE[0] = bool(active)


def device_trace_active() -> bool:
    return _DEVICE_TRACE[0]


class Span:
    """One finished span. ``t0``/``t1`` are monotonic seconds."""

    __slots__ = ("name", "span_id", "parent_id", "t0", "t1", "attrs",
                 "tid", "tid_name")

    def __init__(self, name, span_id, parent_id, t0, t1, attrs,
                 tid=None, tid_name=None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs or {}
        # thread identity is captured at record time (export would see the
        # exporter's thread); tid overrides place spans on virtual rows
        self.tid = (tid if tid is not None
                    else threading.get_ident() % 1_000_000)
        self.tid_name = tid_name

    @property
    def duration(self) -> float:
        return self.t1 - self.t0

    def __repr__(self):
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, dur={self.duration * 1e3:.3f}ms)")


class Tracer:
    """Bounded in-process span log. Finished spans append under a lock;
    beyond ``capacity`` the oldest are evicted (``dropped`` counts them) —
    tracing a long serving run must never grow without bound."""

    def __init__(self, capacity: int = 65536):
        self.capacity = int(capacity)
        self._spans: list[Span] = []
        self._lock = locksan.Lock("tracing.ring")
        self.dropped = 0

    # -- recording -------------------------------------------------------
    def emit(self, name, t0, t1, attrs=None, parent_id=None,
             tid=None, tid_name=None) -> Span | None:
        """Record an already-timed span (monotonic seconds)."""
        if not ENABLED[0]:
            return None
        sp = Span(name, next(_SPAN_IDS), parent_id, float(t0), float(t1),
                  dict(attrs) if attrs else {}, tid=tid, tid_name=tid_name)
        with self._lock:
            self._spans.append(sp)
            if len(self._spans) > self.capacity:
                excess = len(self._spans) - self.capacity
                del self._spans[:excess]
                self.dropped += excess
        return sp

    # -- inspection ------------------------------------------------------
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name: str) -> list[Span]:
        return [s for s in self.spans() if s.name == name]

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # -- export ----------------------------------------------------------
    def export_chrome(self, path: str) -> str:
        """Write the log as a Chrome trace-event JSON file. Spans map to
        complete ("X") events; named virtual threads get thread_name
        metadata so per-request rows are labeled in the viewer."""
        pid = os.getpid()
        events = []
        tid_names = {}
        for s in self.spans():
            tid = s.tid
            if s.tid_name:
                tid_names[tid] = s.tid_name
            args = {k: v for k, v in s.attrs.items()}
            args["span_id"] = s.span_id
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            args["trace_id"] = _TRACE_ID
            events.append({
                "ph": "X", "name": s.name, "pid": pid, "tid": tid,
                "ts": round((s.t0 - _EPOCH) * 1e6, 3),
                "dur": round((s.t1 - s.t0) * 1e6, 3),
                "args": args,
            })
        for tid, name in sorted(tid_names.items()):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": name}})
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms",
                       "otherData": {"trace_id": _TRACE_ID,
                                     "epoch_unix": epoch_unix()}},
                      f, default=str)
        return path


_DEFAULT = Tracer()


def tracer() -> Tracer:
    """The process-global tracer every built-in layer records into."""
    return _DEFAULT


def _stack():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


class _SpanCtx:
    """The live half of :func:`span`: tracks t0, the thread-local parent,
    and (while a device trace runs) a jax TraceAnnotation."""

    __slots__ = ("name", "attrs", "tracer", "span_id", "parent_id",
                 "t0", "_ann", "span")

    def __init__(self, name, attrs, tracer_):
        self.name = name
        self.attrs = attrs
        self.tracer = tracer_
        self.span_id = None
        self.parent_id = None
        self.t0 = None
        self._ann = None
        self.span = None

    def __enter__(self):
        if not ENABLED[0]:
            return self
        self.span_id = next(_SPAN_IDS)
        st = _stack()
        self.parent_id = st[-1] if st else None
        st.append(self.span_id)
        if _DEVICE_TRACE[0]:
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # lint: allow-silent(never let telemetry break the caller)
                self._ann = None
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.span_id is None:      # disabled at entry
            return False
        t1 = time.monotonic()
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
            self._ann = None
        st = _stack()
        if st and st[-1] == self.span_id:
            st.pop()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        sp = Span(self.name, self.span_id, self.parent_id, self.t0, t1,
                  self.attrs)
        with self.tracer._lock:
            self.tracer._spans.append(sp)
            if len(self.tracer._spans) > self.tracer.capacity:
                excess = len(self.tracer._spans) - self.tracer.capacity
                del self.tracer._spans[:excess]
                self.tracer.dropped += excess
        self.span = sp
        return False


def span(name: str, **attrs) -> _SpanCtx:
    """``with span("engine.decode", batch=4): ...`` — records a nested span
    into the global tracer (and the device trace, when one is active)."""
    return _SpanCtx(name, attrs, _DEFAULT)
