"""Performance observability: why did it recompile, where did the memory
go, and which phase of the step got slower.

The generic telemetry primitives (metrics/tracing/flight recorder) record
*what happened*; this module answers the three questions that actually
explain TPU performance — the role of the reference's
``paddle/fluid/platform/profiler`` statistics layer:

- :class:`CompileWatcher` — every jit entry point in the repo (eager op
  dispatch, ``static.Executor``'s trace cache, the serving engine's
  bucketed prefill/decode traces, Pallas kernel builds) reports each
  invocation's *abstract argument signature* here. A signature never seen
  for that callable is a (re)trace: it is counted, timed, and recorded as
  a ``compile.trace`` flight event. Too many distinct signatures for one
  callable inside a sliding window is a **recompilation storm** —
  ``recompile_storms_total`` fires and :func:`explain_recompile` diffs the
  last two signatures, naming exactly which argument's shape/dtype
  churned. A ``jax.monitoring`` listener additionally times the *real*
  XLA backend compiles (``xla_backend_compile_seconds``), catching
  compiles our wrappers cannot see (Pallas inner builds, jax-internal
  retraces).

- :class:`MemoryMonitor` — per-tag live/peak byte accounting (``params``,
  ``opt_state``, ``kv_pool``, ``activations_estimate``, anything a caller
  registers), a bounded timeline, a peak-attribution snapshot ("what was
  live at peak"), ``device_stats()`` passthrough when the backend exposes
  ``Device.memory_stats()``, and a leak sentinel that flags monotonic
  steady-state watermark growth across steps/requests.

- :class:`StepTimeline` — segments train steps and decode steps into
  phases (``data``, ``h2d``, ``compute``, ``collective``, ``update``,
  ``other``) from explicit ``phase()`` contexts plus external attribution
  (eager collectives report their wall time into the active step via
  :func:`note_phase`), reports per-phase percentiles over a rolling
  window, and names the culprit phase when step time regresses against
  its rolling baseline (``step.regression`` flight event).

One process-global instance of each (:func:`compile_watcher`,
:func:`memory_monitor`, :func:`step_timeline`), published through the
metrics registry so the cluster aggregator and ``tools/cluster_status.py``
show fleet-wide recompile storms and memory watermarks per rank.
``tools/perf_gate.py`` turns bench JSONs stamped with :func:`run_meta`
into an enforced perf trajectory against ``BASELINE.json``.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from types import SimpleNamespace

from .flight_recorder import record_event
from .metrics import ENABLED, registry
from ..analysis import locksan

__all__ = [
    "CompileWatcher", "MemoryMonitor", "StepTimeline",
    "compile_watcher", "memory_monitor", "step_timeline",
    "abstract_signature", "explain_recompile", "note_phase",
    "watch_dispatch", "arm_jax_monitoring", "run_meta", "reset",
]

# compile wall times: traces are 10ms..minutes, not sub-ms
_COMPILE_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

_PM = None


def _perf_metrics() -> SimpleNamespace:
    """Lazy family resolve (the module is imported by telemetry/__init__;
    registering at import time is fine, but lazy keeps reset() simple)."""
    global _PM
    if _PM is None:
        reg = registry()
        _PM = SimpleNamespace(
            compiles=reg.counter(
                "xla_compiles_total",
                "(re)traces observed per watched jit callable",
                ("callable",)),
            compile_s=reg.histogram(
                "xla_compile_seconds",
                "wall time of an observed (re)trace, incl. backend compile",
                ("callable",), buckets=_COMPILE_BUCKETS),
            backend_s=reg.histogram(
                "xla_backend_compile_seconds",
                "real XLA backend compiles (jax.monitoring listener)",
                buckets=_COMPILE_BUCKETS),
            storms=reg.counter(
                "recompile_storms_total",
                "recompilation storms (same callable, too many distinct "
                "signatures in a window)", ("callable",)),
            signatures=reg.gauge(
                "compile_signatures_live",
                "distinct argument signatures seen per watched callable",
                ("callable",)),
            mem_live=reg.gauge("memory_live_bytes",
                               "live bytes per accounting tag", ("tag",)),
            mem_peak=reg.gauge("memory_peak_bytes",
                               "peak bytes per accounting tag", ("tag",)),
            leaks=reg.counter(
                "memory_leak_flags_total",
                "leak-sentinel trips (monotonic watermark growth)",
                ("tag",)),
            step_s=reg.histogram("step_time_seconds",
                                 "wall time of one timeline step",
                                 ("timeline",)),
            phase_s=reg.histogram("step_phase_seconds",
                                  "wall time of one step phase",
                                  ("timeline", "phase")),
            regressions=reg.counter(
                "step_regressions_total",
                "steps slower than the rolling baseline, by culprit phase",
                ("timeline", "phase")),
        )
    return _PM


# ---------------------------------------------------------------------------
# signatures
# ---------------------------------------------------------------------------

def _leaf_sig(name, x):
    """One argument's abstract signature entry: (name, shape, dtype)."""
    v = getattr(x, "_value", x)          # unwrap paddle_tpu Tensor
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None and dtype is not None:
        return (name, tuple(int(s) for s in shape), str(dtype))
    # python scalars trace as weak-typed () arrays: dtype-per-type, not
    # value-per-value, so only the type matters for retraces
    return (name, (), f"py:{type(x).__name__}")


def abstract_signature(args, argnames=None) -> tuple:
    """Abstract (shape, dtype) signature of a positional argument list —
    the retrace key jit effectively uses. ``argnames`` labels the entries
    so :func:`explain_recompile` can name the churning argument."""
    out = []
    for i, a in enumerate(args):
        name = argnames[i] if argnames and i < len(argnames) else f"arg{i}"
        out.append(_leaf_sig(name, a))
    return tuple(out)


def _diff_signatures(before: tuple, after: tuple) -> list[dict]:
    """Which argument changed between two signatures, field by field."""
    changes = []
    a_by = {e[0]: e for e in before}
    b_by = {e[0]: e for e in after}
    for name, (_, shp_b, dt_b) in b_by.items():
        if name not in a_by:
            changes.append({"arg": name, "field": "added",
                            "before": None, "after": (shp_b, dt_b)})
            continue
        _, shp_a, dt_a = a_by[name]
        if shp_a != shp_b:
            changes.append({"arg": name, "field": "shape",
                            "before": shp_a, "after": shp_b})
        if dt_a != dt_b:
            changes.append({"arg": name, "field": "dtype",
                            "before": dt_a, "after": dt_b})
    for name in a_by:
        if name not in b_by:
            changes.append({"arg": name, "field": "removed",
                            "before": a_by[name][1:], "after": None})
    return changes


# ---------------------------------------------------------------------------
# CompileWatcher
# ---------------------------------------------------------------------------

class CompileWatcher:
    """Counts and times (re)traces per jit callable, keyed by abstract
    argument signature, and detects recompilation storms.

    ``storm_threshold`` distinct signatures for one callable within
    ``storm_window_s`` is a storm (default 4 in 60s; ``$PADDLE_TPU_STORM_N``
    / ``$PADDLE_TPU_STORM_WINDOW_S`` override). A storm latches until the
    window drains so one churning argument doesn't fire per call.
    """

    def __init__(self, storm_threshold: int | None = None,
                 storm_window_s: float | None = None,
                 max_signatures: int = 256):
        self.storm_threshold = int(
            storm_threshold if storm_threshold is not None
            else os.environ.get("PADDLE_TPU_STORM_N", 4))
        self.storm_window_s = float(
            storm_window_s if storm_window_s is not None
            else os.environ.get("PADDLE_TPU_STORM_WINDOW_S", 60.0))
        self.max_signatures = int(max_signatures)
        self._lock = locksan.Lock("perf.compile_watcher")
        # name -> OrderedDict[signature -> hit count] (insertion-ordered:
        # the last two keys are the last two distinct signatures)
        self._sigs: dict[str, OrderedDict] = {}
        self._recent: dict[str, deque] = {}   # name -> deque[(t, sig)]
        self._storm: dict[str, dict] = {}     # latched storm per name
        self.compiles_total = 0

    # -- recording -------------------------------------------------------
    def record_call(self, name: str, signature: tuple,
                    wall_s: float | None = None,
                    cost: dict | None = None) -> bool:
        """One invocation of a watched callable. Returns True when the
        signature is new for ``name`` (i.e. this call (re)traced).
        ``cost`` is an optional roofline estimate (``telemetry.cost``)
        registered at trace time — it rides the ``compile.trace`` flight
        event so every recorded (re)trace names its modeled FLOPs/bytes."""
        if not ENABLED[0]:
            return False
        now = time.monotonic()
        with self._lock:
            sigs = self._sigs.setdefault(name, OrderedDict())
            if signature in sigs:
                sigs[signature] += 1
                return False
            if len(sigs) >= self.max_signatures:
                sigs.popitem(last=False)
            sigs[signature] = 1
            self.compiles_total += 1
            recent = self._recent.setdefault(
                name, deque(maxlen=4 * max(self.storm_threshold, 4)))
            recent.append((now, signature))
            distinct = self._distinct_in_window(name, now)
            storm = (distinct >= self.storm_threshold
                     and name not in self._storm)
            if storm:
                self._storm[name] = {
                    "callable": name, "distinct_signatures": distinct,
                    "window_s": self.storm_window_s, "t": now,
                }
            elif name in self._storm:
                self._storm[name]["distinct_signatures"] = distinct
            n_sigs = len(sigs)
        pm = _perf_metrics()
        pm.compiles.labels(callable=name).inc()
        pm.signatures.labels(callable=name).set(n_sigs)
        if wall_s is not None:
            pm.compile_s.labels(callable=name).observe(wall_s)
        extra = {}
        if cost:
            extra = {"flops": cost.get("flops"),
                     "bytes": cost.get("bytes"),
                     "arithmetic_intensity":
                         round(cost.get("arithmetic_intensity", 0.0), 3)}
        record_event("compile.trace", callable=name,
                     wall_s=wall_s, distinct=n_sigs,
                     args=[f"{n}:{s}:{d}" for n, s, d in signature][:8],
                     **extra)
        if storm:
            pm.storms.labels(callable=name).inc()
            diff = self.explain(name)
            record_event("compile.storm", callable=name, distinct=distinct,
                         window_s=self.storm_window_s,
                         explain=diff.get("text") if diff else None)
        return True

    def record_compile(self, name: str, signature: tuple, wall_s: float):
        """Direct form for call sites that *know* they compiled (the
        static Executor's cache-miss path)."""
        self.record_call(name, signature, wall_s=wall_s)

    def wrap(self, fn, name: str, argnames=None):
        """Wrap a (jitted) callable: each call reports its signature; a
        new signature's call is timed as the compile wall time (trace +
        backend compile + first run — the cost the caller actually paid)."""
        def wrapped(*args, **kwargs):
            sig = abstract_signature(args, argnames)
            with self._lock:
                new = sig not in self._sigs.get(name, ())
            t0 = time.monotonic()
            out = fn(*args, **kwargs)
            self.record_call(name, sig,
                             wall_s=time.monotonic() - t0 if new else None)
            return out
        wrapped.__name__ = f"watched[{name}]"
        return wrapped

    # -- inspection ------------------------------------------------------
    def _distinct_in_window(self, name, now) -> int:
        recent = self._recent.get(name)
        if not recent:
            return 0
        cutoff = now - self.storm_window_s
        while recent and recent[0][0] < cutoff:
            recent.popleft()
        if not recent and name in self._storm:
            del self._storm[name]    # window drained: un-latch
        return len({sig for _, sig in recent})

    def signatures(self, name: str) -> list[tuple]:
        with self._lock:
            return list(self._sigs.get(name, ()))

    def compiles(self, name: str | None = None) -> int:
        with self._lock:
            if name is None:
                return self.compiles_total
            return len(self._sigs.get(name, ()))

    def storms(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._storm.values()]

    def explain(self, name: str | None = None) -> dict | None:
        """Signature diff for ``name`` (default: the stormiest / most
        recently churning callable): which argument's shape or dtype
        changed between the last two distinct signatures."""
        with self._lock:
            if name is None:
                if self._storm:
                    name = max(self._storm,
                               key=lambda n: self._storm[n].get(
                                   "distinct_signatures", 0))
                elif self._sigs:
                    name = max(self._sigs, key=lambda n: len(self._sigs[n]))
                else:
                    return None
            sigs = list(self._sigs.get(name, ()))
        if len(sigs) < 2:
            return None
        before, after = sigs[-2], sigs[-1]
        changes = _diff_signatures(before, after)
        parts = []
        for c in changes:
            if c["field"] in ("shape", "dtype"):
                parts.append(
                    f"arg '{c['arg']}' {c['field']} "
                    f"{c['before']} -> {c['after']}")
            else:
                parts.append(f"arg '{c['arg']}' {c['field']}")
        text = (f"{name}: {len(sigs)} distinct signatures; last retrace "
                f"changed " + ("; ".join(parts) if parts
                               else "nothing visible (same signature?)"))
        return {"callable": name, "distinct_signatures": len(sigs),
                "changed_args": changes, "text": text}

    def summary(self, prefix: str | None = None) -> dict:
        with self._lock:
            names = [n for n in self._sigs
                     if prefix is None or n.startswith(prefix)]
            out = {
                "compiles_total": sum(len(self._sigs[n]) for n in names),
                "callables": {n: {"compiles": len(self._sigs[n]),
                                  "calls": sum(self._sigs[n].values())}
                              for n in names},
                "storms": [dict(self._storm[n]) for n in names
                           if n in self._storm],
            }
        return out

    def clear(self):
        with self._lock:
            self._sigs.clear()
            self._recent.clear()
            self._storm.clear()
            self.compiles_total = 0


# ---------------------------------------------------------------------------
# MemoryMonitor
# ---------------------------------------------------------------------------

class MemoryMonitor:
    """Per-tag live/peak byte accounting with a peak-attribution snapshot,
    a bounded timeline, and a monotonic-growth leak sentinel.

    Callers register what they allocate (``add``/``sub``) or assert an
    absolute level (``set``); :meth:`note_step` stamps an end-of-step
    watermark per tag — ``leak_window`` consecutive nondecreasing,
    net-growing watermarks flag the tag as leaking (once per streak).
    """

    def __init__(self, timeline_cap: int = 1024, leak_window: int = 8):
        self._lock = locksan.Lock("perf.memory_monitor")
        self._live: dict[str, float] = {}
        self._peak: dict[str, float] = {}
        self._total_peak = 0.0
        self._peak_snapshot: dict[str, float] = {}
        self._timeline: deque = deque(maxlen=int(timeline_cap))
        self.leak_window = int(leak_window)
        self._steps: dict[str, deque] = {}    # tag -> end-of-step watermarks
        self._leak_flagged: set[str] = set()
        # tags whose monotonic growth is expected by design (a
        # capacity-bounded pool filling up, e.g. the KV spill tier): the
        # sentinel only flags them past their declared cap (never, if the
        # cap is None)
        self._bounded: dict[str, float | None] = {}

    # -- accounting ------------------------------------------------------
    def add(self, tag: str, nbytes: float):
        self._update(tag, nbytes, relative=True)

    def sub(self, tag: str, nbytes: float):
        self._update(tag, -nbytes, relative=True)

    def set(self, tag: str, nbytes: float):
        self._update(tag, nbytes, relative=False)

    def expect_bounded(self, tag: str, cap_bytes: float | None = None):
        """Declare ``tag``'s growth expected by design (a pool that fills
        to a capacity and stays there — spill tiers, arenas). The leak
        sentinel stops flagging monotonic growth of the tag while it is
        at or under ``cap_bytes``; with ``cap_bytes=None`` it is never
        flagged. Growth *past* the cap still flags: a bounded pool
        exceeding its bound is precisely a leak."""
        with self._lock:
            self._bounded[tag] = (None if cap_bytes is None
                                  else float(cap_bytes))

    def _update(self, tag, nbytes, relative):
        if not ENABLED[0]:
            return
        with self._lock:
            cur = self._live.get(tag, 0.0)
            new = max(0.0, cur + nbytes if relative else float(nbytes))
            self._live[tag] = new
            peak = max(new, self._peak.get(tag, 0.0))
            self._peak[tag] = peak
            total = sum(self._live.values())
            if total > self._total_peak:
                self._total_peak = total
                self._peak_snapshot = dict(self._live)
            self._timeline.append(
                {"t": time.monotonic(), "tag": tag, "live": new,
                 "total": total})
        pm = _perf_metrics()
        pm.mem_live.labels(tag=tag).set(new)
        pm.mem_peak.labels(tag=tag).set(peak)

    # -- inspection ------------------------------------------------------
    def live(self, tag: str | None = None) -> float:
        with self._lock:
            if tag is None:
                return sum(self._live.values())
            return self._live.get(tag, 0.0)

    def peak(self, tag: str | None = None) -> float:
        with self._lock:
            if tag is None:
                return self._total_peak
            return self._peak.get(tag, 0.0)

    def peak_attribution(self) -> dict:
        """What was live, per tag, at the moment the total peaked."""
        with self._lock:
            return {"total_peak_bytes": self._total_peak,
                    "live_at_peak": dict(self._peak_snapshot)}

    def timeline(self) -> list[dict]:
        with self._lock:
            return list(self._timeline)

    def device_stats(self) -> dict | None:
        """``jax.Device.memory_stats()`` of device 0 when the backend
        exposes it (TPU: bytes_in_use / peak_bytes_in_use / ...); None on
        backends that don't (CPU)."""
        try:
            import jax
            return jax.local_devices()[0].memory_stats()
        except Exception:  # lint: allow-silent(memory_stats unsupported on this backend)
            return None

    def snapshot(self) -> dict:
        with self._lock:
            tags = {t: {"live_bytes": self._live.get(t, 0.0),
                        "peak_bytes": self._peak.get(t, 0.0)}
                    for t in sorted(set(self._live) | set(self._peak))}
            out = {"tags": tags,
                   "total_live_bytes": sum(self._live.values()),
                   "total_peak_bytes": self._total_peak,
                   "live_at_peak": dict(self._peak_snapshot)}
        out["device"] = self.device_stats()
        out["leaks"] = self.leak_report()
        return out

    # -- leak sentinel ---------------------------------------------------
    def note_step(self):
        """Stamp the end-of-step watermark for every tracked tag (call at
        step/request boundaries — steady state should oscillate, not
        climb)."""
        if not ENABLED[0]:
            return
        flagged = []
        with self._lock:
            for tag, live in self._live.items():
                d = self._steps.setdefault(
                    tag, deque(maxlen=self.leak_window))
                d.append(live)
                if tag in self._bounded:
                    cap = self._bounded[tag]
                    if cap is None or live <= cap:
                        self._leak_flagged.discard(tag)
                        continue
                if self._is_leaking(d):
                    if tag not in self._leak_flagged:
                        self._leak_flagged.add(tag)
                        flagged.append((tag, d[-1] - d[0]))
                else:
                    self._leak_flagged.discard(tag)
        for tag, growth in flagged:
            _perf_metrics().leaks.labels(tag=tag).inc()
            record_event("memory.leak", tag=tag, growth_bytes=growth,
                         window_steps=self.leak_window)

    def _is_leaking(self, d: deque) -> bool:
        if len(d) < self.leak_window:
            return False
        vals = list(d)
        return (all(b >= a for a, b in zip(vals, vals[1:]))
                and vals[-1] > vals[0])

    def leak_report(self) -> dict:
        with self._lock:
            return {tag: {"growth_bytes": self._steps[tag][-1]
                          - self._steps[tag][0],
                          "window_steps": len(self._steps[tag])}
                    for tag in sorted(self._leak_flagged)}

    def clear(self):
        with self._lock:
            self._live.clear()
            self._peak.clear()
            self._total_peak = 0.0
            self._peak_snapshot = {}
            self._timeline.clear()
            self._steps.clear()
            self._leak_flagged.clear()
            self._bounded.clear()


# ---------------------------------------------------------------------------
# StepTimeline
# ---------------------------------------------------------------------------

PHASES = ("data", "h2d", "compute", "collective", "update", "other")

_TLS = threading.local()


def _step_stack() -> list:
    st = getattr(_TLS, "steps", None)
    if st is None:
        st = _TLS.steps = []
    return st


def note_phase(phase: str, seconds: float):
    """Attribute ``seconds`` to ``phase`` of the innermost active step on
    this thread (no-op otherwise) — how eager collectives land in the
    ``collective`` phase without the step loop knowing about them."""
    st = getattr(_TLS, "steps", None)
    if st:
        st[-1].note(phase, seconds)


class _StepCtx:
    __slots__ = ("timeline", "t0", "phases")

    def __init__(self, timeline):
        self.timeline = timeline
        self.t0 = None
        self.phases: dict[str, float] = {}

    def note(self, phase, seconds):
        self.phases[phase] = self.phases.get(phase, 0.0) + float(seconds)

    def __enter__(self):
        self.t0 = time.monotonic()
        _step_stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        st = _step_stack()
        if st and st[-1] is self:
            st.pop()
        if exc_type is None and ENABLED[0]:
            self.timeline.record_step(time.monotonic() - self.t0,
                                      self.phases)
        return False


class _PhaseCtx:
    __slots__ = ("step", "name", "t0")

    def __init__(self, step, name):
        self.step = step
        self.name = name

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.step is not None:
            self.step.note(self.name, time.monotonic() - self.t0)
        return False


def _pct(sorted_vals: list, q: float):
    """Nearest-rank-with-interpolation percentile of an ascending list."""
    if not sorted_vals:
        return None
    k = q * (len(sorted_vals) - 1)
    lo = int(k)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = k - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


class StepTimeline:
    """Rolling per-phase step-time accounting with regression attribution.

    ``with tl.step():`` opens a step; ``with tl.phase("data"):`` (or
    :func:`note_phase` from anywhere below) attributes wall time inside
    it. Un-attributed time lands in ``other``. After ``min_baseline``
    steps, a step slower than ``regress_factor`` x the rolling median is
    a regression: the culprit is the phase that grew most over its own
    median, recorded in ``step_regressions_total{timeline,phase}`` and a
    ``step.regression`` flight event.
    """

    def __init__(self, name: str, window: int = 128,
                 regress_factor: float = 1.5, min_baseline: int = 8):
        self.name = name
        self.window = int(window)
        self.regress_factor = float(regress_factor)
        self.min_baseline = int(min_baseline)
        self._lock = locksan.Lock("perf.step_timeline")
        self._totals: deque = deque(maxlen=self.window)
        self._phases: dict[str, deque] = {}
        self.steps = 0
        self.regressions = 0
        self.last_regression: dict | None = None

    def step(self) -> _StepCtx:
        return _StepCtx(self)

    def phase(self, name: str) -> _PhaseCtx:
        st = _step_stack()
        # attribute to this timeline's innermost step (or any active one)
        mine = next((s for s in reversed(st) if s.timeline is self),
                    st[-1] if st else None)
        return _PhaseCtx(mine, name)

    # -- the core record (step() feeds it; tests can too) ---------------
    def record_step(self, total_s: float, phases: dict):
        if not ENABLED[0]:
            return    # telemetry.disable(): one flag check, like every
        total_s = float(total_s)  # other write path
        attributed = sum(phases.values())
        phases = dict(phases)
        phases["other"] = max(0.0, total_s - attributed)
        with self._lock:
            baseline = _pct(sorted(self._totals), 0.5)
            n_prior = len(self._totals)
            self._totals.append(total_s)
            for ph, v in phases.items():
                self._phases.setdefault(
                    ph, deque(maxlen=self.window)).append(v)
            self.steps += 1
        pm = _perf_metrics()
        pm.step_s.labels(timeline=self.name).observe(total_s)
        for ph, v in phases.items():
            if v > 0:
                pm.phase_s.labels(timeline=self.name, phase=ph).observe(v)
        if (baseline is not None and n_prior >= self.min_baseline
                and total_s > self.regress_factor * baseline):
            self._flag_regression(total_s, baseline, phases)

    def _flag_regression(self, total_s, baseline, phases):
        culprit, growth = "other", float("-inf")
        with self._lock:
            for ph, v in phases.items():
                hist = list(self._phases.get(ph, ()))[:-1]
                ph_base = _pct(sorted(hist), 0.5) or 0.0
                if v - ph_base > growth:
                    culprit, growth = ph, v - ph_base
            self.regressions += 1
            self.last_regression = {
                "step_s": total_s, "baseline_s": baseline,
                "culprit": culprit, "culprit_growth_s": max(growth, 0.0),
            }
        _perf_metrics().regressions.labels(
            timeline=self.name, phase=culprit).inc()
        record_event("step.regression", timeline=self.name,
                     step_s=round(total_s, 6),
                     baseline_s=round(baseline, 6), culprit=culprit)

    # -- inspection ------------------------------------------------------
    def report(self) -> dict:
        with self._lock:
            totals = sorted(self._totals)
            if not totals:
                return {"timeline": self.name, "steps": 0}
            total_sum = sum(totals)
            out = {
                "timeline": self.name,
                "steps": self.steps,
                "step_s": {"p50": _pct(totals, 0.5),
                           "p90": _pct(totals, 0.9),
                           "p99": _pct(totals, 0.99),
                           "mean": total_sum / len(totals)},
                "phases": {},
                "regressions": self.regressions,
                "last_regression": (dict(self.last_regression)
                                    if self.last_regression else None),
            }
            for ph, d in self._phases.items():
                vals = sorted(d)
                s = sum(vals)
                out["phases"][ph] = {
                    "p50": _pct(vals, 0.5), "p90": _pct(vals, 0.9),
                    "p99": _pct(vals, 0.99),
                    "mean": s / len(vals),
                    "frac": s / total_sum if total_sum else 0.0,
                }
        return out

    def clear(self):
        with self._lock:
            self._totals.clear()
            self._phases.clear()
            self.steps = 0
            self.regressions = 0
            self.last_regression = None


# ---------------------------------------------------------------------------
# process-global instances + hooks
# ---------------------------------------------------------------------------

_WATCHER = CompileWatcher()
_MEMORY = MemoryMonitor()
_TIMELINES: dict[str, StepTimeline] = {}
_TIMELINES_LOCK = locksan.Lock("perf.timelines")
_MONITORING_ARMED = [False]


def compile_watcher() -> CompileWatcher:
    """The process-global watcher every jit entry point reports into
    (arming the jax.monitoring backend-compile listener on first use)."""
    arm_jax_monitoring()
    return _WATCHER


def memory_monitor() -> MemoryMonitor:
    return _MEMORY


def step_timeline(name: str) -> StepTimeline:
    """Get-or-create the named timeline ("train", "decode", ...)."""
    tl = _TIMELINES.get(name)
    if tl is None:
        with _TIMELINES_LOCK:
            tl = _TIMELINES.setdefault(name, StepTimeline(name))
    return tl


def explain_recompile(name: str | None = None) -> dict | None:
    """Module-level shorthand: the global watcher's signature diff."""
    return _WATCHER.explain(name)


def arm_jax_monitoring():
    """Register a ``jax.monitoring`` duration listener so *real* XLA
    backend compiles (including ones our wrappers cannot see: Pallas
    inner builds, jax-internal retraces) land in
    ``xla_backend_compile_seconds`` + ``compile.backend`` flight events.
    Idempotent; a jax without the API is skipped silently."""
    if _MONITORING_ARMED[0]:
        return
    _MONITORING_ARMED[0] = True
    try:
        import jax.monitoring as jmon

        def _listener(event, duration, **kw):
            if not event.endswith("backend_compile_duration"):
                return
            if not ENABLED[0]:
                return
            _perf_metrics().backend_s.observe(duration)
            record_event("compile.backend", seconds=round(duration, 6))

        jmon.register_event_duration_secs_listener(_listener)
    except Exception:  # lint: allow-silent(older jax without the monitoring listener API)
        pass


def watch_dispatch(enable: bool = True):
    """Opt-in eager-dispatch watching: every ``core.dispatch.apply`` op
    reports its tensor signature as ``dispatch.<op>`` (eager jax caches
    per-shape exactly like jit, so signature churn here is real retrace
    churn). Off by default — it is the one hook on a true hot path."""
    from ..core import dispatch as _dispatch

    if enable:
        def _hook(op_name, tensor_leaves):
            sig = tuple(_leaf_sig(f"in{i}", t)
                        for i, t in enumerate(tensor_leaves))
            _WATCHER.record_call(f"dispatch.{op_name}", sig)
        _dispatch._perf_watch = _hook
    else:
        _dispatch._perf_watch = None


def run_meta() -> dict:
    """The ``__meta__`` stamp bench artifacts carry so ``perf_gate`` can
    refuse cross-platform comparisons: git sha, jax version, platform,
    host, wall time."""
    meta = {"wall_time": time.time(),
            "python": sys.version.split()[0],
            "host": socket.gethostname(),
            "pid": os.getpid()}
    try:
        import jax
        meta["jax_version"] = jax.__version__
        meta["platform"] = jax.devices()[0].platform
    except Exception:  # lint: allow-silent(absence is recorded as None in the report)
        meta["jax_version"] = meta["platform"] = None
    try:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        meta["git_sha"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=repo, timeout=5,
            capture_output=True, text=True).stdout.strip() or None
    except Exception:  # lint: allow-silent(absence is recorded as None in the report)
        meta["git_sha"] = None
    return meta


def reset():
    """Clear every monitor's state (tests / chaos isolation). Metric
    families stay registered; their values persist (counters are
    cumulative by design)."""
    _WATCHER.clear()
    _MEMORY.clear()
    with _TIMELINES_LOCK:
        for tl in _TIMELINES.values():
            tl.clear()
