"""Flight recorder: a bounded ring buffer of recent runtime events, dumped
to disk when something dies.

Production postmortems need the *last few thousand things that happened* —
which collectives launched with what sizes, which blocks the allocator
handed out, which requests were admitted or preempted, which faults the
chaos harness injected — at the moment a ``CollectiveTimeoutError``,
``StoreTimeout``, engine stall, or uncaught exception fires. Logging all of
that continuously is too expensive and mostly noise; a ring buffer keeps
the tail cheap (deque append under a lock) and :meth:`FlightRecorder.dump`
turns it into a JSON artifact on demand.

Dump triggers wired in by the built-in layers (each names its reason):

- ``distributed.collective`` — on :class:`CollectiveTimeoutError`
- ``distributed.tcp_store`` — on :class:`StoreTimeout`
- ``serving.engine`` — when the no-progress stall detector fails a request
- :func:`install_excepthook` — any uncaught (fatal) exception

Dumps land under ``$PADDLE_TPU_FLIGHT_DIR`` (default: the system temp dir)
as ``flightrec-<pid>-<n>.json``; ``last_dump_path`` remembers the newest so
harnesses (``tools/chaos_run.py``) can attach it to their reports. Dumping
never raises: a postmortem writer that crashes the process it is trying to
autopsy is worse than no dump.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import threading
import time
from collections import deque

from .metrics import ENABLED
from ..analysis import locksan

__all__ = ["FlightRecorder", "flight", "record_event", "dump",
           "install_excepthook", "register_context_provider",
           "unregister_context_provider"]

_DUMP_IDS = itertools.count(1)

# name -> zero-arg callable returning a JSON-able blob. Every dump calls
# each provider and attaches the results under doc["context"][name] — how
# the metrics history (telemetry/history.py) rides along on every
# postmortem without the recorder knowing it exists. A provider that
# raises contributes an error marker instead of killing the dump.
_CONTEXT_PROVIDERS: dict[str, object] = {}


def register_context_provider(name: str, fn):
    _CONTEXT_PROVIDERS[str(name)] = fn


def unregister_context_provider(name: str):
    _CONTEXT_PROVIDERS.pop(str(name), None)


def _gather_context() -> dict:
    out = {}
    for name, fn in sorted(_CONTEXT_PROVIDERS.items()):
        try:
            out[name] = fn()
        except Exception as e:  # lint: allow-silent(a broken provider must not kill the postmortem; marker says which one)
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


class FlightRecorder:
    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._buf: deque[dict] = deque(maxlen=self.capacity)
        self._lock = locksan.Lock("flight.ring")
        self._seq = 0
        self.num_dumps = 0
        self.last_dump_path: str | None = None

    # -- recording -------------------------------------------------------
    def record(self, kind: str, **fields):
        """Append one event: {seq, t (monotonic), wall, kind, **fields}.
        Oldest events fall off the ring beyond ``capacity``."""
        if not ENABLED[0]:
            return
        with self._lock:
            self._seq += 1
            self._buf.append({
                "seq": self._seq,
                "t": time.monotonic(),
                "wall": time.time(),
                "kind": kind,
                **fields,
            })

    # -- inspection ------------------------------------------------------
    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._buf)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def __len__(self):
        return len(self._buf)

    def clear(self):
        with self._lock:
            self._buf.clear()
            self._seq = 0

    # -- the postmortem artifact -----------------------------------------
    def _default_dir(self) -> str:
        return os.environ.get("PADDLE_TPU_FLIGHT_DIR",
                              tempfile.gettempdir())

    def dump(self, path: str | None = None, reason: str = "",
             error: BaseException | None = None) -> str | None:
        """Write the ring to ``path`` (default: flightrec-<pid>-<n>.json
        under $PADDLE_TPU_FLIGHT_DIR or the temp dir). Returns the path, or
        None if the write failed — dumping never raises."""
        try:
            if path is None:
                d = self._default_dir()
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"flightrec-{os.getpid()}-{next(_DUMP_IDS)}.json")
            with self._lock:
                evs = list(self._buf)
            doc = {
                "reason": reason,
                "error": (f"{type(error).__name__}: {error}"
                          if error is not None else None),
                "pid": os.getpid(),
                "wall_time": time.time(),
                "num_events": len(evs),
                "events_dropped": max(0, self._seq - len(evs)),
                "events": evs,
            }
            if _CONTEXT_PROVIDERS:
                doc["context"] = _gather_context()
            with open(path, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            self.num_dumps += 1
            self.last_dump_path = path
            return path
        except Exception:  # lint: allow-silent(dump is best-effort; None tells the caller it failed)
            return None


_GLOBAL = FlightRecorder()


def flight() -> FlightRecorder:
    """The process-global recorder every built-in layer records into."""
    return _GLOBAL


def record_event(kind: str, **fields):
    _GLOBAL.record(kind, **fields)


def dump(reason: str = "", error: BaseException | None = None,
         path: str | None = None) -> str | None:
    return _GLOBAL.dump(path=path, reason=reason, error=error)


_HOOK_INSTALLED = [False]


def install_excepthook():
    """Chain onto ``sys.excepthook`` so any uncaught exception dumps the
    flight recorder before the process dies (idempotent). KeyboardInterrupt
    and SystemExit are deliberate, not crashes — no dump for those."""
    if _HOOK_INSTALLED[0]:
        return
    _HOOK_INSTALLED[0] = True
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
            _GLOBAL.record("fatal.exception", type=exc_type.__name__,
                           message=str(exc)[:500])
            _GLOBAL.dump(reason="uncaught exception", error=exc)
        prev(exc_type, exc, tb)

    sys.excepthook = hook
