"""paddle.save / paddle.load
(reference: /root/reference/python/paddle/framework/io.py:646,888 — pickled
nested state_dicts of numpy-converted tensors). Same wire idea: nested
containers with Tensors converted to numpy, pickled. Orbax handles the
sharded/async checkpoint path (paddle_tpu.distributed.checkpoint)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_MAGIC = b"PDTPU1\n"


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return _TensorLeaf(np.asarray(obj._value), stop_gradient=obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(v) for v in obj)
    return obj


def _from_numpy_tree(obj, return_numpy=False):
    if isinstance(obj, _TensorLeaf):
        if return_numpy:
            return obj.array
        t = Tensor(obj.array)
        t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _from_numpy_tree(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_numpy_tree(v, return_numpy) for v in obj)
    return obj


class _TensorLeaf:
    __slots__ = ("array", "stop_gradient")

    def __init__(self, array, stop_gradient=True):
        self.array = array
        self.stop_gradient = stop_gradient


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(_to_numpy_tree(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head != _MAGIC:
            f.seek(0)
        obj = pickle.load(f)
    return _from_numpy_tree(obj, return_numpy=return_numpy)
