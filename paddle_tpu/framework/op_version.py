"""Op-version / program-compat registry (reference
/root/reference/paddle/fluid/framework/op_version_registry.h — the
mechanism that lets a serialized program declare which revision of each
op's semantics it was built against, so artifact evolution is defined
rather than accidental).

TPU-native shape: exported archives (jit.save / save_inference_model /
onnx.export) embed a ``.pdversion`` JSON sidecar with the framework
version, the serialization IR, and the op-version table snapshot; loaders
call :func:`check_compat` which (a) accepts artifacts whose op versions
are <= the live registry's (older semantics are upgradable), and
(b) rejects artifacts carrying NEWER op versions with an actionable error
(the reference's IsProgramVersionSupported role,
paddle/fluid/framework/program_utils.cc).
"""
from __future__ import annotations

import json
import os

__all__ = [
    "FRAMEWORK_VERSION", "register_op_version", "op_version",
    "version_snapshot", "write_version_file", "read_version_file",
    "check_compat",
]

FRAMEWORK_VERSION = "0.5.0"  # round-5 build
_IR = "stablehlo+jax.export"

# op -> (version, changelog). Seeded with the ops whose semantics have
# already evolved ACROSS ROUNDS of this framework — the registry exists so
# the next change is recorded here, not silently.
_REGISTRY: dict[str, tuple[int, str]] = {}


def register_op_version(op: str, version: int, note: str):
    cur = _REGISTRY.get(op, (0, ""))[0]
    if version <= cur:
        raise ValueError(
            f"op_version({op!r}): new version {version} must exceed {cur}")
    _REGISTRY[op] = (version, note)


def op_version(op: str) -> int:
    return _REGISTRY.get(op, (0, ""))[0]


# --- seeded history (semantics changes shipped in earlier rounds) ---------
register_op_version(
    "flash_attn_unpadded", 2,
    "r5: real cu_seqlens varlen kernel; r4 and earlier aliased the padded "
    "path (artifacts saved before r5 never contained true varlen graphs)")
register_op_version(
    "max_pool2d_with_index", 2,
    "r5: returns real argmax indices into the flattened input plane; "
    "earlier rounds returned the pooled values only")
register_op_version(
    "reduce", 2,
    "r5: rank-asymmetric dst semantics (non-dst ranks keep their input); "
    "earlier rounds broadcast the reduction to every rank")
register_op_version(
    "dropout", 2,
    "r4: eval-mode downscale_in_infer honored; r3 ignored mode")


def version_snapshot() -> dict:
    return {
        "framework_version": FRAMEWORK_VERSION,
        "ir": _IR,
        "op_versions": {k: v for k, (v, _) in _REGISTRY.items()},
    }


def write_version_file(path_prefix: str):
    """Sidecar next to the artifact: <prefix>.pdversion."""
    with open(path_prefix + ".pdversion", "w") as f:
        json.dump(version_snapshot(), f, indent=1)


def read_version_file(path_prefix: str) -> dict | None:
    p = path_prefix + ".pdversion"
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def check_compat(meta: dict | None, origin: str = "artifact"):
    """Raise if the artifact claims NEWER op semantics than this build
    provides; tolerate absent metadata (pre-r5 artifacts) and older
    versions (this build can execute their graphs)."""
    if meta is None:
        return  # pre-versioning artifact: jax.export's own IR versioning
        # still guards deserialization
    if meta.get("ir") not in (None, _IR):
        raise RuntimeError(
            f"{origin}: serialized with IR {meta.get('ir')!r}; this build "
            f"loads {_IR!r}")
    newer = {
        op: v for op, v in (meta.get("op_versions") or {}).items()
        if v > op_version(op)
    }
    if newer:
        raise RuntimeError(
            f"{origin}: built against newer op semantics than this "
            f"framework provides: { {k: f'artifact v{v} > runtime v{op_version(k)}' for k, v in newer.items()} }. "
            "Upgrade paddle_tpu or re-export the model.")
