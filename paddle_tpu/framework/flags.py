"""Global flags tier (reference: paddle.set_flags/get_flags over 91 exported
gflags, /root/reference/paddle/phi/core/flags.cc +
paddle/fluid/pybind/global_value_getter_setter.cc).

TPU-native: one typed in-process registry seeded from FLAGS_* environment
variables (the reference's env override path), consumed by the dispatch layer
(nan/inf checks), the kernel policy, and XLA knob plumbing. Unknown flags
raise, matching the reference's enforce behavior.
"""
from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = ["set_flags", "get_flags", "register_flag"]


@dataclass
class _Flag:
    name: str
    default: object
    doc: str
    value: object = None

    def __post_init__(self):
        self.value = self.default


_REGISTRY: dict[str, _Flag] = {}


def register_flag(name: str, default, doc: str = ""):
    """Declare a flag (framework modules call this at import). Env var of the
    same name overrides the default, like the reference's gflags env hook."""
    flag = _Flag(name, default, doc)
    env = os.environ.get(name)
    if env is not None:
        flag.value = _coerce(env, default)
    _REGISTRY[name] = flag
    return flag


def _coerce(text, like):
    if isinstance(like, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(like, int):
        return int(text)
    if isinstance(like, float):
        return float(text)
    return text


def set_flags(flags: dict):
    """paddle.set_flags({"FLAGS_check_nan_inf": True})"""
    for name, value in flags.items():
        if name not in _REGISTRY:
            raise ValueError(
                f"unknown flag {name!r}; known: {sorted(_REGISTRY)}")
        cur = _REGISTRY[name]
        cur.value = _coerce(value, cur.default) if isinstance(value, str) else value


def get_flags(names):
    """paddle.get_flags("FLAGS_check_nan_inf") or a list of names."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        if n not in _REGISTRY:
            raise ValueError(
                f"unknown flag {n!r}; known: {sorted(_REGISTRY)}")
        out[n] = _REGISTRY[n].value
    return out


def flag_value(name: str):
    """Fast internal accessor (no dict copy) for hot paths."""
    return _REGISTRY[name].value


# -- the exported flag set (reference flags.cc roles that survive on TPU) ----
register_flag("FLAGS_check_nan_inf", False,
              "check every op output for NaN/Inf and raise with the op name "
              "(reference nan_inf_utils_detail.cc)")
register_flag("FLAGS_use_pallas", "",
              "'1'/'0' force the Pallas kernel path on/off; empty = platform "
              "default (PHI kernel-key selection role)")
register_flag("FLAGS_benchmark", False,
              "block on every op result (like the reference's stream-sync "
              "benchmark mode) — makes per-op timing honest")
register_flag("FLAGS_dy2static_eager_fallback", False,
              "explicit opt-in: let to_static fall back to eager execution "
              "(with a warning) when control flow can't be compiled; default "
              "raises — silent eager dispatch is a 10-100x TPU perf cliff")
register_flag("FLAGS_cudnn_deterministic", False,
              "determinism request; XLA:TPU is deterministic by default so "
              "this only pins rng-behind-dropout choices")
register_flag("FLAGS_allocator_strategy", "auto_growth",
              "accepted for API parity; XLA's BFC allocator is the "
              "implementation either way")
register_flag("FLAGS_fault_plan", "",
              "chaos harness: ';'-separated fault specs "
              "(site:kind[=arg][@start][xcount][%prob]) armed at every "
              "paddle_tpu.utils.faults.inject site — see docs/ROBUSTNESS.md")
register_flag("FLAGS_locksan", False,
              "arm the LockSan runtime lock-order sanitizer "
              "(paddle_tpu.analysis.locksan): instrumented locks record "
              "acquisition order and blocking-calls-under-lock — set at "
              "process start so module-level locks are created armed; see "
              "docs/ANALYSIS.md")
register_flag("FLAGS_collective_timeout_s", 0.0,
              "when > 0, every eager collective runs under a watchdog that "
              "raises CollectiveTimeoutError naming the op/group/rank if the "
              "call does not complete in this many seconds")
