"""Global RNG state.

The reference keeps one ``phi::Generator`` per device with seed control
(/root/reference/paddle/phi/core/generator.h) plus a distributed RNG-state
tracker for TP determinism. Here the state is a JAX PRNG key:

- eager ops split the global key statefully (paddle.seed reproducibility);
- under jit/functional tracing an ``rng_scope`` supplies a (possibly traced)
  base key; call sites derive keys with ``fold_in(base, counter)`` where the
  counter advances at trace time — one deterministic stream per call site,
  varying per step through the traced base key (the idiomatic-JAX replacement
  for a mutable generator inside a compiled program).
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np

_state = threading.local()
# key is materialized lazily: building it at import would initialize JAX
# backends before a launcher can call jax.distributed.initialize
_global = {"seed": 0, "key": None}
_np_state = {"rng": None}


def _global_key():
    if _global["key"] is None:
        _global["key"] = jax.random.PRNGKey(_global["seed"])
    return _global["key"]


def seed(s: int):
    """paddle.seed: reset the global generator."""
    _global["seed"] = int(s)
    _global["key"] = jax.random.PRNGKey(int(s))
    _np_state["rng"] = np.random.RandomState(int(s))
    return _global["key"]


def np_rng() -> np.random.RandomState:
    """Host-side numpy generator tied to paddle.seed — parameter
    initialization runs on host, not through the traced PRNG streams."""
    if _np_state["rng"] is None:
        _np_state["rng"] = np.random.RandomState(_global["seed"])
    return _np_state["rng"]


def get_cuda_rng_state():  # parity shim
    return [_global_key()]


@contextlib.contextmanager
def rng_scope(key):
    """Install a base key for functional tracing (jit/grad)."""
    prev = getattr(_state, "scope", None)
    _state.scope = {"key": key, "counter": 0}
    try:
        yield
    finally:
        _state.scope = prev


def next_key():
    """Get a fresh PRNG key: stateful split in eager, fold_in under a scope."""
    scope = getattr(_state, "scope", None)
    if scope is not None:
        k = jax.random.fold_in(scope["key"], scope["counter"])
        scope["counter"] += 1
        return k
    _global["key"], sub = jax.random.split(_global_key())
    return sub


def default_seed() -> int:
    return _global["seed"]


def _key_data(key):
    """Raw uint32 words of a PRNG key (typed keys included)."""
    try:
        return np.asarray(key)
    except TypeError:  # new-style typed key array
        return np.asarray(jax.random.key_data(key))


def get_rng_state() -> dict:
    """Picklable snapshot of every host-side RNG stream: the paddle.seed
    value, the current global PRNG key (mutated by eager splits), and the
    numpy host generator. Checkpointing this alongside params is what makes
    resume *bit*-deterministic — a restarted process replays exactly the
    random draws an uninterrupted one would have made."""
    key = _global["key"]
    rng = _np_state["rng"]
    return {
        "seed": _global["seed"],
        "key": None if key is None else _key_data(key),
        "np_state": None if rng is None else rng.get_state(),
    }


def set_rng_state(state: dict):
    """Restore a :func:`get_rng_state` snapshot (checkpoint-resume path)."""
    _global["seed"] = int(state.get("seed", 0))
    key = state.get("key")
    _global["key"] = None if key is None else jax.numpy.asarray(
        np.asarray(key, np.uint32))
    nps = state.get("np_state")
    if nps is None:
        _np_state["rng"] = None
    else:
        rng = np.random.RandomState()
        rng.set_state(nps)
        _np_state["rng"] = rng
