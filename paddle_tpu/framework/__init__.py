from . import random  # noqa: F401
from .random import seed  # noqa: F401
