from . import random  # noqa: F401
from .random import seed  # noqa: F401

from . import op_version  # noqa: F401,E402
