"""paddle.incubate parity (reference /root/reference/python/paddle/incubate/
— fused nn ops, extra optimizers, ASP 2:4 sparsity, autotune config).

On TPU "fused" ops are XLA fusions: the incubate names bind to the same
bodies the kernel policy already fuses, so the namespace is API parity, not
a second implementation.
"""
from . import asp  # noqa: F401
from . import nn  # noqa: F401
from .optimizer import (  # noqa: F401
    DistributedFusedLamb, LookAhead, ModelAverage)

__all__ = ["nn", "asp", "LookAhead", "ModelAverage",
           "DistributedFusedLamb", "autotune"]


def autotune(config=None):
    """reference incubate.autotune: kernel/dataloader/amp autotuning toggles.
    XLA autotunes its own GEMM/conv algorithms during compilation; accepted
    for API parity and recorded on the kernel-policy module."""
    from .. import kernels

    config = config or {}
    if "kernel" in config and "enable" in config["kernel"]:
        # explicit True/False is an override either way (None = no override)
        kernels.set_use_pallas(bool(config["kernel"]["enable"]))
    return config
