"""incubate.asp: 2:4 structured sparsity (reference incubate/asp/ —
Automatic SParsity: prune masks so every 4 consecutive weights keep the 2
largest; sparse tensor cores accelerate this on GPU, the capability here is
the pruning workflow + mask maintenance)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["calculate_density", "create_mask", "check_mask_2d4",
           "prune_model", "decorate"]


def create_mask(weight, n=2, m=4):
    """Keep the n largest magnitudes of every m consecutive elements along
    the LAST axis (groups never cross rows — the 2:4 hardware layout)."""
    w = np.asarray(weight.numpy() if hasattr(weight, "numpy") else weight)
    if w.shape[-1] % m != 0:
        raise ValueError(
            f"last dim {w.shape[-1]} not divisible by m={m}; 2:{m} groups "
            "must lie within a row")
    grouped = w.reshape(-1, m)  # row-major: groups stay inside the last axis
    idx = np.argsort(-np.abs(grouped), axis=1)[:, :n]
    mask = np.zeros_like(grouped, dtype=bool)
    np.put_along_axis(mask, idx, True, axis=1)
    return mask.reshape(w.shape)


def check_mask_2d4(mask, n=2, m=4):
    ms = np.asarray(mask).reshape(-1, m)
    return bool(np.all(ms.sum(axis=1) == n))


def calculate_density(weight):
    w = np.asarray(weight.numpy() if hasattr(weight, "numpy") else weight)
    return float(np.count_nonzero(w) / w.size)


def prune_model(model, n=2, m=4, mask_algo="mask_1d"):
    """Apply 2:4 masks to every Linear weight in place; masks are recorded on
    the layer so `decorate`d optimizers can re-apply after updates."""
    from ..nn.layers.common import Linear

    masks = {}
    for name, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, Linear):
            if layer.weight.shape[-1] % m != 0:
                continue  # ragged head (e.g. 10-class classifier): the
                # reference likewise skips non-conforming layers
            mask = create_mask(layer.weight, n, m)
            layer.weight._value = layer.weight._value * jnp.asarray(mask)
            layer._asp_mask = jnp.asarray(mask)
            masks[name] = mask
    return masks


def decorate(optimizer, model=None):
    """Wrap optimizer.step to re-apply recorded masks after every update
    (reference asp.decorate keeps pruned weights at zero during training)."""
    inner_step = optimizer.step
    layers = ([l for _, l in model.named_sublayers(include_self=True)
               if hasattr(l, "_asp_mask")] if model is not None else [])

    def masked_step(*a, **k):
        out = inner_step(*a, **k)
        for l in layers:
            l.weight._value = l.weight._value * l._asp_mask
        return out

    optimizer.step = masked_step
    return optimizer
