"""incubate.nn fused layers/functional (reference incubate/nn/: fused
attention/feedforward/transformer, memory_efficient_attention). The bodies
are the existing attention/FFN compositions — XLA produces the fusion the
reference hand-writes in CUDA."""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.transformer import TransformerEncoderLayer

__all__ = [
    "FusedMultiHeadAttention", "FusedFeedForward", "FusedTransformerEncoderLayer",
    "fused_multi_head_attention", "fused_feedforward",
    "memory_efficient_attention",
]


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    return F.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_bias, dropout_p=p, scale=scale,
        training=training)


def fused_multi_head_attention(x, qkv_weight=None, out_weight=None, **kwargs):
    raise NotImplementedError(
        "use incubate.nn.FusedMultiHeadAttention (layer form); the raw-weight "
        "functional form is CUDA-kernel-specific plumbing")


def fused_feedforward(x, w1, b1, w2, b2, activation="relu"):
    h = F.linear(x, w1, b1)
    h = getattr(F, activation)(h)
    return F.linear(h, w2, b2)


class FusedMultiHeadAttention(Layer):
    """API-parity wrapper over MultiHeadAttention: same math, XLA fuses the
    projections+attention (flash kernel on chip)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, normalize_before=False, **kwargs):
        super().__init__()
        from ..nn.layers.norm import LayerNorm
        from ..nn.layers.transformer import MultiHeadAttention

        self.normalize_before = normalize_before
        self.attn = MultiHeadAttention(embed_dim, num_heads,
                                       dropout=attn_dropout_rate)
        self.norm = LayerNorm(embed_dim)
        from ..nn.layers.common import Dropout

        self.dropout = Dropout(dropout_rate)

    def forward(self, x, attn_mask=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        out = self.attn(x, x, x, attn_mask)
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", normalize_before=False, **kwargs):
        super().__init__()
        from ..nn.layers.common import Dropout, Linear
        from ..nn.layers.norm import LayerNorm

        self.normalize_before = normalize_before
        self.fc1 = Linear(d_model, dim_feedforward)
        self.fc2 = Linear(dim_feedforward, d_model)
        self.norm = LayerNorm(d_model)
        self.dropout = Dropout(dropout_rate)
        self.activation = activation

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        h = getattr(F, self.activation)(self.fc1(x))
        out = residual + self.dropout(self.fc2(h))
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(TransformerEncoderLayer):
    """Same block as TransformerEncoderLayer — the fusion is XLA's job."""
