"""incubate.nn fused layers/functional (reference incubate/nn/: fused
attention/feedforward/transformer, memory_efficient_attention). The bodies
are the existing attention/FFN compositions — XLA produces the fusion the
reference hand-writes in CUDA."""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.transformer import TransformerEncoderLayer

__all__ = [
    "FusedMultiHeadAttention", "FusedFeedForward", "FusedTransformerEncoderLayer",
    "fused_multi_head_attention", "fused_feedforward",
    "memory_efficient_attention",
]


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    return F.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_bias, dropout_p=p, scale=scale,
        training=training)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None,
        attn_mask=None, dropout_rate=0.5, attn_dropout_rate=0.5,
        ln_epsilon=1e-5, training=True, mode="upscale_in_train", ring_id=-1,
        add_residual=True, num_heads=-1, transpose_qkv_wb=False, name=None):
    """Raw-weight fused self-attention (reference
    /root/reference/python/paddle/incubate/nn/functional/fused_transformer.py:465
    — the reference hand-writes this fusion in CUDA; here it is ONE traced
    body XLA fuses, with the Pallas flash kernel carrying the attention).
    qkv_weight: [3, num_heads, head_dim, embed] (or [embed, 3*embed] with
    transpose_qkv_wb=True and num_heads set)."""
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def body(xv, qkv_w, lin_w, *rest):
        names = [n for n, v in optional if v is not None]
        extras = dict(zip(names, rest))
        residual = xv
        h = xv
        if pre_layer_norm:
            mu = jnp.mean(h, -1, keepdims=True)
            var = jnp.var(h, -1, keepdims=True)
            h = (h - mu) / jnp.sqrt(var + pre_ln_epsilon)
            if "pre_ln_scale" in extras:
                h = h * extras["pre_ln_scale"]
            if "pre_ln_bias" in extras:
                h = h + extras["pre_ln_bias"]
        B, S, E = h.shape
        if transpose_qkv_wb:
            nh = int(num_heads)
            qkv = h @ qkv_w  # [B,S,3E]
            if "qkv_bias" in extras:
                qkv = qkv + extras["qkv_bias"]
            qkv = qkv.reshape(B, S, 3, nh, E // nh)
        else:
            nh = qkv_w.shape[1]
            hd = qkv_w.shape[2]
            qkv = jnp.einsum("bse,knde->bskn d".replace(" ", ""), h,
                             qkv_w)  # [B,S,3,nh,hd]
            if "qkv_bias" in extras:
                qkv = qkv + extras["qkv_bias"].reshape(3, nh, hd)
        q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])  # [B,S,nh,hd]
        from ..kernels import attention_impl

        out = attention_impl()(
            q, k, v, attn_mask=attn_mask,
            dropout_p=attn_dropout_rate if training else 0.0,
            is_causal=False, training=training)
        out = out.reshape(B, S, E)
        out = out @ (lin_w if lin_w.ndim == 2
                     else lin_w.reshape(E, E))
        if "linear_bias" in extras:
            out = out + extras["linear_bias"]
        if dropout_rate and training:
            import jax

            from ..framework.random import next_key

            keep = jax.random.bernoulli(next_key(), 1.0 - dropout_rate,
                                        out.shape)
            out = out * keep.astype(out.dtype) / (1.0 - dropout_rate)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            mu = jnp.mean(out, -1, keepdims=True)
            var = jnp.var(out, -1, keepdims=True)
            out = (out - mu) / jnp.sqrt(var + ln_epsilon)
            if "ln_scale" in extras:
                out = out * extras["ln_scale"]
            if "ln_bias" in extras:
                out = out + extras["ln_bias"]
        return out

    optional = [("pre_ln_scale", pre_ln_scale), ("pre_ln_bias", pre_ln_bias),
                ("ln_scale", ln_scale), ("ln_bias", ln_bias),
                ("qkv_bias", qkv_bias), ("linear_bias", linear_bias)]
    extra_args = [v for _, v in optional if v is not None]
    return apply(body, x, qkv_weight, linear_weight, *extra_args,
                 op_name="fused_multi_head_attention")


def fused_feedforward(x, w1, b1, w2, b2, activation="relu"):
    h = F.linear(x, w1, b1)
    h = getattr(F, activation)(h)
    return F.linear(h, w2, b2)


class FusedMultiHeadAttention(Layer):
    """API-parity wrapper over MultiHeadAttention: same math, XLA fuses the
    projections+attention (flash kernel on chip)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, normalize_before=False, **kwargs):
        super().__init__()
        from ..nn.layers.norm import LayerNorm
        from ..nn.layers.transformer import MultiHeadAttention

        self.normalize_before = normalize_before
        self.attn = MultiHeadAttention(embed_dim, num_heads,
                                       dropout=attn_dropout_rate)
        self.norm = LayerNorm(embed_dim)
        from ..nn.layers.common import Dropout

        self.dropout = Dropout(dropout_rate)

    def forward(self, x, attn_mask=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        out = self.attn(x, x, x, attn_mask)
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", normalize_before=False, **kwargs):
        super().__init__()
        from ..nn.layers.common import Dropout, Linear
        from ..nn.layers.norm import LayerNorm

        self.normalize_before = normalize_before
        self.fc1 = Linear(d_model, dim_feedforward)
        self.fc2 = Linear(dim_feedforward, d_model)
        self.norm = LayerNorm(d_model)
        self.dropout = Dropout(dropout_rate)
        self.activation = activation

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        h = getattr(F, self.activation)(self.fc1(x))
        out = residual + self.dropout(self.fc2(h))
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(TransformerEncoderLayer):
    """Same block as TransformerEncoderLayer — the fusion is XLA's job."""
