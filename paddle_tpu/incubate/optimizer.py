"""incubate optimizers (reference incubate/optimizer/: LookAhead
(Zhang 2019) and ModelAverage) as wrappers over any inner optimizer."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k fast steps with the inner optimizer, then slow weights interpolate:
    slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = None
        self._steps = 0

    def _params(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        if self._slow is None:
            self._slow = [np.asarray(p._value).copy() for p in self._params()]
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p, s in zip(self._params(), self._slow):
                new_slow = s + self.alpha * (np.asarray(p._value) - s)
                p._value = jnp.asarray(new_slow)
                s[...] = new_slow

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        out = self.inner_optimizer.state_dict()
        out["lookahead"] = {"steps": self._steps,
                            "slow": None if self._slow is None
                            else [s.copy() for s in self._slow]}
        return out

    def set_state_dict(self, state):
        la = state.pop("lookahead", None)
        self.inner_optimizer.set_state_dict(state)
        if la:
            self._steps = la["steps"]
            self._slow = la["slow"]


class ModelAverage:
    """Maintain a running average of parameters; apply()/restore() swap the
    averaged weights in for evaluation (reference incubate ModelAverage)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._sum = [jnp.zeros_like(p._value) for p in self._params]
        self._count = 0
        self._backup = None
        self.max_average_window = int(max_average_window)

    def accumulate(self):
        # on-device accumulation (no per-step host transfer); window restart
        # bounds the history like the reference's cascading sum windows
        if self._count >= self.max_average_window:
            self._sum = [jnp.array(p._value) for p in self._params]
            self._count = 1
            return
        self._sum = [s + p._value for s, p in zip(self._sum, self._params)]
        self._count += 1

    # the reference hooks accumulate into step(); standalone usage calls
    # accumulate() after each optimizer.step()
    def step(self):
        self.accumulate()

    def apply(self, executor=None, need_restore=True):
        if self._count == 0:
            return
        self._backup = [jnp.array(p._value) for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._value = s / self._count

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._value = b
        self._backup = None


class DistributedFusedLamb(__import__("paddle_tpu.optimizer",
                                      fromlist=["Lamb"]).Lamb):
    """Parity surface for incubate.DistributedFusedLamb (reference
    python/paddle/incubate/optimizer/distributed_fused_lamb.py — a
    multi-tensor CUDA-fused LAMB whose gradient allreduce/clip fusion is
    hand-written). TPU-native: the SAME update math as Lamb; the
    "distributed fusion" — global-norm clip spanning mesh axes, gradient
    reduction, multi-tensor batching — is what GSPMD+XLA produce from the
    jitted engine step, so the knobs below are accepted for API parity and
    documented as absorbed rather than re-implemented."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, name=None):
        super().__init__(learning_rate, lamb_weight_decay, beta1, beta2,
                         epsilon, parameters, grad_clip,
                         exclude_from_weight_decay_fn, name)
        # absorbed-by-design knobs (kept for signature parity)
        self._fusion_cfg = dict(
            clip_after_allreduce=clip_after_allreduce,
            is_grad_scaled_by_nranks=is_grad_scaled_by_nranks,
            alignment=alignment, use_master_param_norm=use_master_param_norm,
            gradient_accumulation_steps=gradient_accumulation_steps,
            use_master_acc_grad=use_master_acc_grad)
