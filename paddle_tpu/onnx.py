"""paddle.onnx parity (reference python/paddle/onnx/export.py — delegation
to the external paddle2onnx converter).

This environment has no onnx/paddle2onnx dependency (zero-egress image), so
``export`` to a literal ``.onnx`` path emits ONNX **natively**: the layer's
eval-mode forward is traced to a jaxpr (the same graph jax.export would
serialize) and translated primitive-by-primitive into an ONNX GraphProto,
serialized with a self-contained protobuf wire-format writer (the schema
subset of onnx.proto: Model/Graph/Node/Tensor/ValueInfo/Attribute).

Covered primitive set (the exportable-op subset; LeNet/MLP-class models
trace entirely inside it): conv_general_dilated, dot_general, elementwise
arithmetic, min/max, reduce_window (max/avg pooling), reductions,
reshape/transpose/broadcast, cast, sigmoid/tanh/exp/log/sqrt/rsqrt,
integer_pow, select_n, concatenate, pad, squeeze. Anything else raises
with the primitive name (reference parity: paddle2onnx also raises per
unconvertible op).

Non-.onnx paths keep the StableHLO deployment artifact via jit.save.
"""
from __future__ import annotations

import struct

import numpy as np

__all__ = ["export"]


# ---------------------------------------------------------------------------
# minimal protobuf wire-format writer (proto3 subset used by onnx.proto)
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_int(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(int(v))


def _f_bytes(field: int, b: bytes) -> bytes:
    return _key(field, 2) + _varint(len(b)) + b


def _f_str(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode())


def _f_packed_ints(field: int, vals) -> bytes:
    body = b"".join(_varint(int(v)) for v in vals)
    return _f_bytes(field, body)


def _f_float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", float(v))


# ONNX TensorProto.DataType
_DT = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5, "int32": 6,
       "int64": 7, "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = _DT.get(str(arr.dtype))
    if dt is None:
        raise RuntimeError(f"onnx export: unsupported dtype {arr.dtype}")
    return (_f_packed_ints(1, arr.shape)          # dims
            + _f_int(2, dt)                       # data_type
            + _f_str(8, name)                     # name
            + _f_bytes(9, np.ascontiguousarray(arr).tobytes()))  # raw_data


def _value_info(name: str, shape, dtype) -> bytes:
    dims = b"".join(_f_bytes(1, _f_int(1, int(d))) for d in shape)
    tshape = _f_bytes(2, dims)                                 # shape
    ttype = _f_int(1, _DT[str(np.dtype(str(dtype)))]) + tshape
    return _f_str(1, name) + _f_bytes(2, _f_bytes(1, ttype))   # TypeProto


# AttributeProto types
_ATTR_FLOAT, _ATTR_INT, _ATTR_STR = 1, 2, 3
_ATTR_FLOATS, _ATTR_INTS = 6, 7


def _attr(name: str, value) -> bytes:
    out = _f_str(1, name)
    if isinstance(value, bool):
        out += _f_int(3, int(value)) + _f_int(20, _ATTR_INT)
    elif isinstance(value, int):
        out += _f_int(3, value) + _f_int(20, _ATTR_INT)
    elif isinstance(value, float):
        out += _f_float(2, value) + _f_int(20, _ATTR_FLOAT)
    elif isinstance(value, str):
        out += _f_bytes(4, value.encode()) + _f_int(20, _ATTR_STR)
    elif isinstance(value, (list, tuple)) and value and isinstance(
            value[0], float):
        out += b"".join(_key(7, 5) + struct.pack("<f", v) for v in value)
        out += _f_int(20, _ATTR_FLOATS)
    else:  # int list (possibly empty)
        out += _f_packed_ints(8, value) + _f_int(20, _ATTR_INTS)
    return out


def _node(op_type: str, inputs, outputs, name: str, **attrs) -> bytes:
    out = b"".join(_f_str(1, i) for i in inputs)
    out += b"".join(_f_str(2, o) for o in outputs)
    out += _f_str(3, name) + _f_str(4, op_type)
    for k, v in attrs.items():
        out += _f_bytes(5, _attr(k, v))
    return out


# ---------------------------------------------------------------------------
# jaxpr -> ONNX graph
# ---------------------------------------------------------------------------

class _Graph:
    def __init__(self):
        self.nodes: list[bytes] = []
        self.inits: list[bytes] = []
        self.op_types: list[str] = []  # for tests/diagnostics
        self._n = 0

    def name(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def add(self, op, inputs, outputs=None, **attrs):
        outs = outputs or [self.name(op.lower())]
        self.nodes.append(_node(op, inputs, outs,
                                self.name(f"n_{op}"), **attrs))
        self.op_types.append(op)
        return outs[0]

    def const(self, arr, hint="c"):
        arr = np.asarray(arr)
        name = self.name(hint)
        self.inits.append(_tensor_proto(name, arr))
        return name


def _translate(closed_jaxpr, in_names, g: _Graph):
    """Walk jaxpr eqns emitting ONNX nodes; returns output names."""
    from jax.extend import core as jex_core

    env = {}

    def read(var):
        if isinstance(var, jex_core.Literal):
            return g.const(np.asarray(var.val), "lit")
        return env[var]

    jaxpr = closed_jaxpr.jaxpr
    for var, const in zip(jaxpr.constvars, closed_jaxpr.consts):
        env[var] = g.const(np.asarray(const), "w")
    for var, name in zip(jaxpr.invars, in_names):
        env[var] = name

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        ins = [read(v) for v in eqn.invars]
        params = eqn.params

        # --- call-like primitives: inline recursively -------------------
        if prim in ("jit", "pjit", "closed_call", "core_call", "xla_call",
                    "remat2", "checkpoint", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr"):
            inner = (params.get("jaxpr") or params.get("call_jaxpr")
                     or params.get("fun_jaxpr"))
            if inner is None:
                raise RuntimeError(
                    f"onnx export: call primitive {prim!r} without jaxpr")
            if not hasattr(inner, "consts"):
                inner = jex_core.ClosedJaxpr(inner, ())
            sub_names = _translate(inner, ins, g)
            for var, nm in zip(eqn.outvars, sub_names):
                env[var] = nm
            continue

        h = _PRIMS.get(prim)
        if h is None:
            raise RuntimeError(
                f"onnx export: primitive {prim!r} has no ONNX lowering "
                f"(supported: {sorted(_PRIMS)})")
        h(g, eqn, ins, env)

    return [read(v) for v in jaxpr.outvars]


def _ew(op):
    def h(g, eqn, ins, env):
        env[eqn.outvars[0]] = g.add(op, ins)

    return h


def _h_conv(g, eqn, ins, env):
    p = eqn.params
    dn = p["dimension_numbers"]
    if tuple(dn.lhs_spec) != tuple(range(len(dn.lhs_spec))):
        raise RuntimeError("onnx export: conv expects NCHW lhs layout")
    if any(int(d) != 1 for d in p.get("lhs_dilation", ())):
        raise RuntimeError(
            "onnx export: lhs-dilated conv (conv_transpose) has no "
            "ConvTranspose lowering yet — export the forward model only")
    pads_cfg = p["padding"]
    n_sp = len(p["window_strides"])
    pads = [pr[0] for pr in pads_cfg] + [pr[1] for pr in pads_cfg]
    env[eqn.outvars[0]] = g.add(
        "Conv", ins, strides=list(map(int, p["window_strides"])),
        pads=list(map(int, pads)),
        dilations=list(map(int, p["rhs_dilation"])),
        group=int(p["feature_group_count"]),
        kernel_shape=[int(d)
                      for d in eqn.invars[1].aval.shape[2:2 + n_sp]])


def _h_dot(g, eqn, ins, env):
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    l_nd = len(eqn.invars[0].aval.shape)
    r_nd = len(eqn.invars[1].aval.shape)
    if lb or rb:
        raise RuntimeError("onnx export: batched dot_general unsupported")
    if tuple(lc) == (l_nd - 1,) and tuple(rc) == (0,):
        env[eqn.outvars[0]] = g.add("MatMul", ins)
        return
    if tuple(lc) == (l_nd - 1,) and tuple(rc) == (r_nd - 1,):
        t = g.add("Transpose", [ins[1]],
                  perm=list(range(r_nd - 2)) + [r_nd - 1, r_nd - 2])
        env[eqn.outvars[0]] = g.add("MatMul", [ins[0], t])
        return
    raise RuntimeError(
        f"onnx export: dot_general contraction "
        f"{eqn.params['dimension_numbers']} unsupported")


def _h_reduce_window(g, eqn, ins, env):
    p = eqn.params
    comp = eqn.primitive.name
    dims = list(map(int, p["window_dimensions"]))
    strides = list(map(int, p["window_strides"]))
    pads_cfg = p["padding"]
    if dims[0] != 1 or dims[1] != 1:
        raise RuntimeError("onnx export: pooling over batch/channel dims")
    pads = ([pr[0] for pr in pads_cfg[2:]] + [pr[1] for pr in pads_cfg[2:]])
    if "max" in comp:
        env[eqn.outvars[0]] = g.add(
            "MaxPool", [ins[0]], kernel_shape=dims[2:], strides=strides[2:],
            pads=list(map(int, pads)))
        return
    # sum-pool: ONNX has no SumPool — AveragePool * prod(k) restores the
    # SUM, so the divide the traced graph itself carries stays correct
    # (count_include_pad matches jax's zero-padded window sum)
    ap = g.add("AveragePool", [ins[0]], kernel_shape=dims[2:],
               strides=strides[2:], pads=list(map(int, pads)),
               count_include_pad=1)
    import numpy as _np

    k = g.const(np.asarray(float(np.prod(dims[2:])), np.float32), "wincount")
    env[eqn.outvars[0]] = g.add("Mul", [ap, k])


def _h_reshape(g, eqn, ins, env):
    shape = g.const(np.asarray(eqn.params["new_sizes"], np.int64), "shape")
    env[eqn.outvars[0]] = g.add("Reshape", [ins[0], shape])


def _h_transpose(g, eqn, ins, env):
    env[eqn.outvars[0]] = g.add(
        "Transpose", ins, perm=list(map(int, eqn.params["permutation"])))


def _h_broadcast(g, eqn, ins, env):
    p = eqn.params
    out_shape = list(map(int, p["shape"]))
    bdims = p["broadcast_dimensions"]
    interim = [1] * len(out_shape)
    in_shape = eqn.invars[0].aval.shape
    for i, d in enumerate(bdims):
        interim[d] = int(in_shape[i])
    shape1 = g.const(np.asarray(interim, np.int64), "shape")
    r = g.add("Reshape", [ins[0], shape1])
    shape2 = g.const(np.asarray(out_shape, np.int64), "shape")
    env[eqn.outvars[0]] = g.add("Expand", [r, shape2])


def _h_cast(g, eqn, ins, env):
    dt = _DT.get(str(np.dtype(eqn.params["new_dtype"])))
    if dt is None:
        raise RuntimeError(
            f"onnx export: cast to {eqn.params['new_dtype']} unsupported")
    env[eqn.outvars[0]] = g.add("Cast", ins, to=dt)


def _h_reduce(op):
    def h(g, eqn, ins, env):
        axes = list(map(int, eqn.params["axes"]))
        if op == "ReduceSum":  # opset 13: axes is an INPUT for ReduceSum
            ax = g.const(np.asarray(axes, np.int64), "axes")
            env[eqn.outvars[0]] = g.add(op, [ins[0], ax], keepdims=0)
        else:  # ReduceMax/Min keep the attribute form until opset 18
            env[eqn.outvars[0]] = g.add(op, ins, axes=axes, keepdims=0)

    return h


def _h_integer_pow(g, eqn, ins, env):
    y = g.const(np.asarray(eqn.params["y"], np.float32), "pow")
    env[eqn.outvars[0]] = g.add("Pow", [ins[0], y])


def _h_rsqrt(g, eqn, ins, env):
    s = g.add("Sqrt", ins)
    env[eqn.outvars[0]] = g.add("Reciprocal", [s])


def _h_select(g, eqn, ins, env):
    # select_n(pred, on_false, on_true) -> Where(pred, on_true, on_false)
    if len(ins) != 3:
        raise RuntimeError("onnx export: select_n arity != 3")
    env[eqn.outvars[0]] = g.add("Where", [ins[0], ins[2], ins[1]])


def _h_concat(g, eqn, ins, env):
    env[eqn.outvars[0]] = g.add(
        "Concat", ins, axis=int(eqn.params["dimension"]))


def _h_pad(g, eqn, ins, env):
    cfg = eqn.params["padding_config"]
    if any(int(i) != 0 for _, _, i in cfg):
        raise RuntimeError("onnx export: interior padding unsupported")
    pads = [int(lo) for lo, _, _ in cfg] + [int(hi) for _, hi, _ in cfg]
    pads_c = g.const(np.asarray(pads, np.int64), "pads")
    env[eqn.outvars[0]] = g.add("Pad", [ins[0], pads_c, ins[1]])


def _h_squeeze(g, eqn, ins, env):
    dims = list(map(int, eqn.params["dimensions"]))
    axes = g.const(np.asarray(dims, np.int64), "axes")
    env[eqn.outvars[0]] = g.add("Squeeze", [ins[0], axes])


def _h_copy(g, eqn, ins, env):
    env[eqn.outvars[0]] = g.add("Identity", ins)


def _h_argmax(g, eqn, ins, env):
    env[eqn.outvars[0]] = g.add(
        "ArgMax", ins, axis=int(eqn.params["axes"][0]), keepdims=0)


_PRIMS = {
    "add": _ew("Add"), "sub": _ew("Sub"), "mul": _ew("Mul"),
    "div": _ew("Div"), "max": _ew("Max"), "min": _ew("Min"),
    "exp": _ew("Exp"), "log": _ew("Log"), "neg": _ew("Neg"),
    "tanh": _ew("Tanh"), "logistic": _ew("Sigmoid"), "sqrt": _ew("Sqrt"),
    "abs": _ew("Abs"), "floor": _ew("Floor"), "ceil": _ew("Ceil"),
    "sign": _ew("Sign"), "erf": _ew("Erf"), "pow": _ew("Pow"),
    "conv_general_dilated": _h_conv,
    "dot_general": _h_dot,
    "reduce_window_max": _h_reduce_window,
    "reduce_window_sum": _h_reduce_window,
    "reduce_window": _h_reduce_window,
    "reshape": _h_reshape,
    "transpose": _h_transpose,
    "broadcast_in_dim": _h_broadcast,
    "convert_element_type": _h_cast,
    "reduce_sum": _h_reduce("ReduceSum"),
    "reduce_max": _h_reduce("ReduceMax"),
    "reduce_min": _h_reduce("ReduceMin"),
    "integer_pow": _h_integer_pow,
    "rsqrt": _h_rsqrt,
    "select_n": _h_select,
    "concatenate": _h_concat,
    "pad": _h_pad,
    "squeeze": _h_squeeze,
    "copy": _h_copy, "stop_gradient": _h_copy,
    "argmax": _h_argmax,
}


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def _example_arrays(input_spec):
    arrays = []
    for spec in input_spec:
        if isinstance(spec, np.ndarray):
            arrays.append(spec)
        elif hasattr(spec, "shape"):  # InputSpec or Tensor
            shape = [1 if (d is None or int(d) < 0) else int(d)
                     for d in spec.shape]
            dtype = getattr(spec, "dtype", "float32") or "float32"
            arrays.append(np.zeros(shape, np.dtype(str(dtype))))
        else:
            raise TypeError(
                f"input_spec entry {type(spec).__name__} not supported")
    return arrays


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Reference signature (python/paddle/onnx/export.py:22). `.onnx` paths
    emit native ONNX; other paths save the StableHLO artifact."""
    if not path.endswith(".onnx"):
        from . import jit

        jit.save(layer, path, input_spec=input_spec)
        return path + ".pdmodel"

    if input_spec is None:
        raise ValueError("onnx export needs input_spec (shapes/examples)")
    import jax

    from .nn.layer import functional_call, functional_state

    layer.eval()
    params, buffers = functional_state(layer)
    examples = _example_arrays(input_spec)

    def forward(*xs):
        out, _ = functional_call(layer, params, buffers, *xs)
        return out

    closed = jax.make_jaxpr(forward)(*[np.asarray(e) for e in examples])

    g = _Graph()
    in_names = [f"input_{i}" for i in range(len(examples))]
    out_names = _translate(closed, in_names, g)

    graph = b"".join(_f_bytes(1, n) for n in g.nodes)
    graph += _f_str(2, type(layer).__name__)
    graph += b"".join(_f_bytes(5, t) for t in g.inits)
    graph += b"".join(
        _f_bytes(11, _value_info(n, e.shape, e.dtype))
        for n, e in zip(in_names, examples))
    for nm, aval in zip(out_names, closed.out_avals):
        graph += _f_bytes(12, _value_info(nm, aval.shape, aval.dtype))

    model = (_f_int(1, 8)                      # ir_version
             + _f_str(2, "paddle_tpu")         # producer_name
             + _f_str(3, "0.5")
             + _f_bytes(8, _f_str(1, "") + _f_int(2, int(opset_version)))
             + _f_bytes(7, graph))
    with open(path, "wb") as f:
        f.write(model)
    return path
