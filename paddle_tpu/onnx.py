"""paddle.onnx parity surface (reference python/paddle/onnx/export.py — a
0.2K-LoC delegation to the external paddle2onnx package).

This build has no ONNX exporter dependency (zero-egress image); ``export``
produces the portable deployment artifact this framework standardizes on —
a serialized StableHLO program + weights via jit.save (loadable by
paddle_tpu.inference and any StableHLO consumer). Requesting a literal
.onnx file raises with instructions, exactly like the reference does when
paddle2onnx isn't installed.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    if path.endswith(".onnx"):
        raise RuntimeError(
            "ONNX serialization needs the external paddle2onnx-equivalent "
            "converter, which is not available in this environment. Use a "
            "prefix path (no .onnx) to export the portable StableHLO "
            "artifact instead; paddle_tpu.inference.Predictor and any "
            "StableHLO toolchain can load it.")
    from . import jit

    jit.save(layer, path, input_spec=input_spec)
    return path + ".pdmodel"
